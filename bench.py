"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE.json primary metric: "ResNet-50 ImageNet images/sec/chip".
The driver runs this on the real chip each round (BENCH_r{N}.json).

One full training step (fwd + loss + bwd + SGD-momentum update) compiled
into a single XLA program via parallel.TrainStep on a 1-device mesh —
the steady-state Gluon hybridize+Trainer path collapsed to its compute.
bf16 compute (MXU-native) with fp32 master math in BN, synthetic data
(the reference's benchmark_score.py / train_imagenet.py --benchmark 1
pattern: measure compute throughput, not input pipeline).

vs_baseline: MXNet-CUDA's classic published ResNet-50 fp16 throughput on
one V100 (~1,41?0 img/s era-dependent; we use 1000 img/s as the nominal
single-accelerator reference from the MXNet model-zoo era benchmarks,
BASELINE.json `published` being empty).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_IMG_PER_SEC = 1000.0  # nominal MXNet-CUDA 1-GPU reference
BATCH = 128
WARMUP = 3
ITERS = 10


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import make_mesh, TrainStep

    mx.random.seed(0)
    np.random.seed(0)

    with mx.Context("cpu"):
        net = vision.resnet50_v1(classes=1000)
        net.initialize(mx.init.Xavier())
        net.cast("bfloat16")
        net(mx.nd.zeros((1, 3, 224, 224), dtype="bfloat16"))  # deferred init

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        onehot = jax.nn.one_hot(labels, 1000, dtype=logp.dtype)
        return -jnp.mean(jnp.sum(logp * onehot, axis=-1))

    mesh = make_mesh(axes=("dp",), devices=jax.devices()[:1])
    step = TrainStep(net, loss_fn, mesh, learning_rate=0.1, momentum=0.9)

    x = jnp.asarray(np.random.randn(BATCH, 3, 224, 224), jnp.bfloat16)
    y = jnp.asarray(np.random.randint(0, 1000, BATCH), jnp.int32)
    xs, ys = step.shard_batch(x, y)

    for _ in range(WARMUP):
        loss = step(xs, ys)
    jax.block_until_ready(loss._jax if hasattr(loss, "_jax") else loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step(xs, ys)
    jax.block_until_ready(loss._jax if hasattr(loss, "_jax") else loss)
    dt = time.perf_counter() - t0

    img_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
