"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE.json primary metric: "ResNet-50 ImageNet images/sec/chip".
The driver runs this on the real chip each round (BENCH_r{N}.json).

One full training step (fwd + loss + bwd + SGD-momentum update) compiled
into a single XLA program via parallel.TrainStep on a 1-device mesh —
the steady-state Gluon hybridize+Trainer path collapsed to its compute.
bf16 compute (MXU-native) with fp32 master math in BN, synthetic data
(the reference's benchmark_score.py / train_imagenet.py --benchmark 1
pattern: measure compute throughput, not input pipeline).

Resilience (round-1 lesson: the TPU tunnel can be wedged, and a bare
`jax.devices()` probe then HANGS, costing the round its bench number):
the parent process probes each backend in a SUBPROCESS with a hard
timeout + retries, then execs the actual benchmark as a child pinned to
the first healthy backend via JAX_PLATFORMS. If every accelerator probe
fails, it falls back to a small CPU run so the driver still records a
numeric value (with "device" marking the fallback), never a traceback.

vs_baseline: MXNet-CUDA's classic published ResNet-50 throughput on one
V100-era GPU; BASELINE.json `published` is empty so we use 1000 img/s as
the nominal single-accelerator reference.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_IMG_PER_SEC = 1000.0  # nominal MXNet-CUDA 1-GPU reference
PROBE_TIMEOUT_S = 150          # first TPU compile can take ~20-40s; be generous
CHILD_TIMEOUT_S = 1200

# ISSUE 13 retrace chase: strict retraces the --eager lane may report.
# Measured 2 after the imperative-pass fix + specializing-site census
# split (was 79); the budget only ever goes DOWN — bench_compare exits
# non-zero on an over-budget report.
EAGER_RETRACE_BUDGET = 4

# Per-chip bf16 peak TFLOP/s by device kind (public cloud.google.com/tpu
# numbers); the MFU gate must use the actual device, not a flat constant.
# ORDERED: specific kinds first — v5p reports device_kind "TPU v5", while
# v5e reports "TPU v5 lite"/"TPU v5e", so the bare "v5" entry (459, v5p)
# must come after every lite spelling.
_TPU_PEAK_TFLOPS = [
    ("v5 lite", 197.0), ("v5litepod", 197.0), ("v5e", 197.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v6 lite", 918.0), ("v6e", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def _device_peak_tflops():
    """bf16 peak for jax.devices()[0], keyed on device_kind; falls back to
    the v5e number when the kind is unrecognized (gauge stays an estimate
    for unknown hardware, but is exact for every kind we can name)."""
    import jax
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 197.0
    for key, peak in _TPU_PEAK_TFLOPS:
        if key in kind:
            return peak
    return 197.0


def _census_report(max_programs=40):
    """Program-census block every bench lane embeds (ISSUE 10): the
    roll-up the regression sentinel gates on (total compile seconds,
    peak temp bytes, retrace count) plus the per-program table, largest
    compile first.  ISSUE 13: the persistent compile-cache roll-up
    (hits/misses/bytes per layer) rides along — the warm-restart
    acceptance reads it."""
    from mxnet_tpu import compile_cache, programs
    table = programs.program_table()
    ranked = sorted(table.values(),
                    key=lambda t: -t["compile_seconds"]["total"])
    dropped = max(0, len(ranked) - max_programs)
    out = {"summary": programs.program_summary(),
           "compile_cache": compile_cache.stats(),
           "programs": {t["name"]: t for t in ranked[:max_programs]}}
    if dropped:
        out["programs_truncated"] = dropped
    return out


def _timed_steps(step, scan, warmup, iters, dev_batch, host_batch):
    """Measure `iters` steps; per-step dispatch loop by default, ONE
    k-step jit (TrainStep.run_steps) with --scan.  In scan mode the first
    timed call absorbs the k-step compile and is discarded (no separate
    warmup executable); returns (loss, dt)."""
    import time as _t
    import jax

    def _sync(x):
        jax.block_until_ready(x._jax if hasattr(x, "_jax") else x)

    if scan:
        loss = step.run_steps(iters, *host_batch)   # compile + warm
        _sync(loss)
        t0 = _t.perf_counter()
        loss = step.run_steps(iters, *host_batch)
        _sync(loss)
        return loss, _t.perf_counter() - t0
    for _ in range(warmup):
        loss = step(*dev_batch)
    _sync(loss)
    t0 = _t.perf_counter()
    for _ in range(iters):
        loss = step(*dev_batch)
    _sync(loss)
    return loss, _t.perf_counter() - t0


def run_bench():
    """The actual benchmark. Runs on jax's default backend (parent pins it)."""
    import jax
    if os.environ.get("MX_BENCH_PLATFORM") == "cpu":
        # The axon plugin force-sets jax_platforms="axon,cpu" (ignores the
        # JAX_PLATFORMS env); override the config back or backend init hangs
        # on a wedged tunnel.
        from mxnet_tpu.base import pin_cpu
        pin_cpu()
    import numpy as np
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import make_mesh, TrainStep

    on_cpu = jax.default_backend() == "cpu"
    # CPU fallback exists only so a wedged tunnel still yields a number:
    # keep it small enough to finish.
    batch = 8 if on_cpu else 256
    warmup = 1 if on_cpu else 5
    iters = 2 if on_cpu else 20

    mx.random.seed(0)
    np.random.seed(0)

    with mx.Context("cpu"):
        net = vision.resnet50_v1(classes=1000)
        net.initialize(mx.init.Xavier())
        net.cast("bfloat16")
        net(mx.nd.zeros((1, 3, 224, 224), dtype="bfloat16"))  # deferred init

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        onehot = jax.nn.one_hot(labels, 1000, dtype=logp.dtype)
        return -jnp.mean(jnp.sum(logp * onehot, axis=-1))

    mesh = make_mesh(axes=("dp",), devices=jax.devices()[:1])
    step = TrainStep(net, loss_fn, mesh, learning_rate=0.1, momentum=0.9)

    x = jnp.asarray(np.random.randn(batch, 3, 224, 224), jnp.bfloat16)
    y = jnp.asarray(np.random.randint(0, 1000, batch), jnp.int32)
    xs, ys = step.shard_batch(x, y)

    scan = os.environ.get("MX_BENCH_SCAN") == "1"
    loss, dt = _timed_steps(step, scan, warmup, iters, (xs, ys), (x, y))

    img_per_sec = batch * iters / dt
    # MFU diagnostic: ResNet-50 fwd+bwd ~= 3x 3.87 GFLOP/img at 224x224.
    tflops = img_per_sec * 3 * 3.87e9 / 1e12
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip"
                  + ("_scan" if scan else ""),
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 4),
        # the denominator is a NOMINAL 1000 img/s (BASELINE.json shipped
        # no published numbers; replace when the reference harness runs)
        "baseline_nominal": True,
        "device": jax.default_backend(),
        "batch": batch,
        "tflops": round(tflops, 2),
    }))


def run_bert_bench():
    """--bert: BERT-base pretraining-style step, tokens/sec/chip (the
    second north-star metric, BASELINE.json).  MLM cross-entropy over a
    whole-step-jitted TrainStep; bf16 activations; seq len 512."""
    import jax
    if os.environ.get("MX_BENCH_PLATFORM") == "cpu":
        from mxnet_tpu.base import pin_cpu
        pin_cpu()
    import numpy as np
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import bert as bert_mod
    from mxnet_tpu.parallel import make_mesh, TrainStep

    on_cpu = jax.default_backend() == "cpu"
    if on_cpu:
        batch, seq, layers, units, heads = 2, 128, 2, 128, 2
        warmup, iters = 1, 2
    else:
        batch, seq, layers, units, heads = 16, 512, 12, 768, 12
        warmup, iters = 3, 10
    vocab = 30522

    mx.random.seed(0)
    np.random.seed(0)
    with mx.Context("cpu"):
        net = bert_mod.get_bert(num_layers=layers, units=units,
                                num_heads=heads, vocab_size=vocab,
                                max_length=seq, dropout=0.0,
                                use_classifier=False)
        net.cast("bfloat16")
        net.initialize(mx.init.Normal(0.02))
        net(mx.nd.zeros((1, seq), dtype="int32"),
            mx.nd.zeros((1, seq), dtype="int32"))

    def loss_fn(outputs, labels):
        mlm = outputs[-1]                    # (B, T, vocab)
        logp = jax.nn.log_softmax(mlm.astype(jnp.float32), axis=-1)
        onehot = jax.nn.one_hot(labels, vocab, dtype=logp.dtype)
        return -jnp.mean(jnp.sum(logp * onehot, axis=-1))

    mesh = make_mesh(axes=("dp",), devices=jax.devices()[:1])
    step = TrainStep(net, loss_fn, mesh, learning_rate=1e-3)
    tok = jnp.asarray(np.random.randint(0, vocab, (batch, seq)), jnp.int32)
    seg = jnp.zeros((batch, seq), jnp.int32)
    lab = jnp.asarray(np.random.randint(0, vocab, (batch, seq)), jnp.int32)
    tok, seg, lab = step.shard_batch(tok, seg, lab)

    scan = os.environ.get("MX_BENCH_SCAN") == "1"
    host = tuple(np.asarray(jax.device_get(a)) for a in (tok, seg, lab))
    loss, dt = _timed_steps(step, scan, warmup, iters,
                            (tok, seg, lab), host)

    tokens_per_sec = batch * seq * iters / dt
    # MEASURED param count (not the 110M folklore number): sum over the
    # block's parameter tree.
    n_params = float(sum(
        int(np.prod(p.shape)) for p in net.collect_params().values()
        if p.shape is not None))
    # fwd+bwd FLOPs/token ≈ 6*N (dense matmuls) + 12*L*s*d (attention
    # scores+apply, quadratic term) — the standard training-FLOPs formula.
    flops_per_token = 6.0 * n_params + 12.0 * layers * seq * units
    tflops = tokens_per_sec * flops_per_token / 1e12
    mfu = tflops / _device_peak_tflops() if not on_cpu else 0.0
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip"
                  + ("_scan" if scan else ""),
        "value": round(tokens_per_sec, 1), "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.5, 4),   # 1.0 == the 50% MFU target
        "device": jax.default_backend(), "batch": batch, "seq": seq,
        "n_params": int(n_params), "peak_tflops": _device_peak_tflops(),
        "tflops": round(tflops, 2), "mfu": round(mfu, 4),
    }))


def run_eager_bench():
    """--eager: Gluon eager-Trainer step throughput, images/sec/chip.

    The steady-state path ISSUE 3 optimized: per-op forward/backward, ONE
    fused optimizer dispatch per step (multi-tensor pytree apply), device-
    side metric accumulation.  Reported next to the TrainStep numbers so
    BENCH rounds can watch the eager-vs-whole-step-jit gap shrink; the
    dispatch counts per step ride along as diagnostics.
    """
    import jax
    if os.environ.get("MX_BENCH_PLATFORM") == "cpu":
        from mxnet_tpu.base import pin_cpu
        pin_cpu()
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon.model_zoo import vision

    on_cpu = jax.default_backend() == "cpu"
    batch = 4 if on_cpu else 64
    warmup = 1 if on_cpu else 3
    iters = 2 if on_cpu else 10

    mx.random.seed(0)
    np.random.seed(0)
    net = vision.resnet18_v1(classes=1000)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    params = list(net.collect_params().values())
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    x_np = np.random.randn(batch, 3, 224, 224).astype(np.float32)
    y_np = np.random.randint(0, 1000, batch).astype(np.float32)
    x = nd.array(x_np)
    y = nd.array(y_np)

    def step(xb, yb):
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out, yb)
        loss.backward()
        trainer.step(batch_size=batch)
        metric.update([yb], [out])
        return loss

    def sync():
        # the loss alone doesn't depend on the step's optimizer update or
        # the metric accumulate — block on those too, or the last step's
        # device work leaks out of the timed window
        jax.block_until_ready(loss._jax)
        jax.block_until_ready(params[0].data()._jax)
        if metric._dev_sum is not None:
            jax.block_until_ready(metric._dev_sum)

    for _ in range(warmup):
        loss = step(x, y)
    sync()

    # ISSUE 13: the timed loop consumes a REAL input stream — fresh
    # host batches crossing to the device each step — so data_wait is a
    # measured phase, not structurally zero.  With MX_PREFETCH (default
    # on) the DevicePrefetcher device_puts one batch ahead off its own
    # thread; with it off the transfer runs synchronously in the loop,
    # observed under the same phase for an honest on/off comparison.
    from mxnet_tpu import telemetry as _tel
    from mxnet_tpu.io.prefetch import DevicePrefetcher, prefetch_enabled

    def batch_stream():
        for _ in range(iters):
            yield (x_np, y_np)

    use_prefetch = prefetch_enabled()

    def _dw_total():
        inst = _tel.registry.find("step_phase_seconds",
                                  {"phase": "data_wait"})
        return inst.snapshot()["sum"] if inst is not None else 0.0

    # ISSUE 10: ONE consistent counter read (snapshot), not racy
    # property-by-property reads mid-step
    snap0 = engine.snapshot()
    dw0 = _dw_total()
    if use_prefetch:
        with DevicePrefetcher(batch_stream()) as pf:
            t0 = time.perf_counter()
            for xb, yb in pf:
                loss = step(nd.NDArray(xb), nd.NDArray(yb))
            sync()
            dt = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        for xb_np, yb_np in batch_stream():
            t_dw = time.perf_counter()
            xb = nd.NDArray(jax.device_put(xb_np))
            yb = nd.NDArray(jax.device_put(yb_np))
            # block on BOTH transfers: an in-flight label copy would
            # escape data_wait and overlap the step, flattering the
            # synchronous baseline
            jax.block_until_ready(xb._jax)
            jax.block_until_ready(yb._jax)
            _tel.observe_phase("data_wait", time.perf_counter() - t_dw)
            loss = step(xb, yb)
        sync()
        dt = time.perf_counter() - t0
    data_wait_s = _dw_total() - dw0
    prefetch_report = {
        "enabled": use_prefetch,
        "data_wait_total_ms": round(data_wait_s * 1e3, 3),
        "data_wait_share_pct": round(100.0 * data_wait_s / dt, 3),
        "gate_pct": 5.0,
        "within_gate": bool(100.0 * data_wait_s / dt < 5.0),
    }
    dispatches = (engine.snapshot()["dispatches"]
                  - snap0["dispatches"]) / iters
    img_per_sec = batch * iters / dt

    # ISSUE 8: telemetry snapshot + measured overhead.  The loop above
    # ran with telemetry ON (the default), so the per-phase histograms
    # already hold this bench's step breakdown; the overhead probe runs
    # separately on the fast MLP eager step (seconds-long CPU resnet
    # steps drown the microsecond-scale span cost in scheduler noise).
    from mxnet_tpu import telemetry
    # snapshot the resnet loop's record BEFORE the overhead probe runs
    # its own MLP steps through the recorder
    last_record = telemetry.flight_recorder.last()
    telemetry_report = {
        "enabled": telemetry.enabled(),
        "phases": telemetry.phase_snapshot(),
        "last_step_record": last_record,
        "overhead": _telemetry_overhead(),
    }

    # ISSUE 7 comparison lane: the SAME workload through the whole-step
    # compiled path (one donated jit per step; lax.scan window amortizes
    # the remaining host round-trip) — BENCH rounds watch this ratio as
    # the eager pipeline's dispatch overhead gets compiled away.
    mx.random.seed(0)
    np.random.seed(0)
    net_c = vision.resnet18_v1(classes=1000)
    net_c.initialize(mx.init.Xavier())
    trainer_c = gluon.Trainer(list(net_c.collect_params().values()), "sgd",
                              {"learning_rate": 0.1, "momentum": 0.9})
    from mxnet_tpu.step import scan_window
    cstep = trainer_c.make_compiled_step(net_c, loss_fn,
                                         metric=mx.metric.Accuracy())
    scan_n = scan_window() or (4 if on_cpu else 16)

    def timed(fn, n):
        # two warm calls: the first finishes deferred init (eager
        # fallback), the second traces + compiles; the third is steady
        # state
        fn()
        fn()
        t0 = time.perf_counter()
        loss = fn()
        jax.block_until_ready(loss._jax)
        return n / (time.perf_counter() - t0)

    compiled_ips = timed(lambda: cstep.step(x, y), batch)
    xw = nd.array(np.broadcast_to(np.asarray(x._jax),
                                  (scan_n,) + tuple(x.shape)).copy())
    yw = nd.array(np.broadcast_to(np.asarray(y._jax),
                                  (scan_n,) + tuple(y.shape)).copy())
    scan_ips = timed(lambda: cstep.run_window(xw, yw), batch * scan_n)

    # ISSUE 13 retrace budget: the eager lane's STRICT retrace count
    # (specializing sites count their expected shape specializations
    # separately) can only go down.  Over-budget poisons the report —
    # tools/bench_compare.py exits non-zero on it.
    census = _census_report()
    retraces = census["summary"]["retraces"]

    # ISSUE 14: --mesh lane — the SpecLayout-sharded step's per-chip
    # params+optimizer bytes (buffer census) and throughput; gated by
    # tools/bench_compare.py as the mesh-class-keyed
    # params_bytes_per_chip series
    sharded = _sharded_lane()

    print(json.dumps({
        "metric": "resnet18_eager_trainer_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 4),
        "baseline_nominal": True,
        "device": jax.default_backend(),
        "batch": batch,
        "dispatches_per_step": round(dispatches, 1),
        "n_params": len(params),
        # eager-vs-compiled, same model/batch (ISSUE 7 acceptance lane)
        "compiled_images_per_sec": round(compiled_ips, 2),
        "compiled_scan_images_per_sec": round(scan_ips, 2),
        "scan_window": scan_n,
        "speedup_compiled_vs_eager": round(compiled_ips / img_per_sec, 2),
        "speedup_scan_vs_eager": round(scan_ips / img_per_sec, 2),
        "dispatch_bound": _dispatch_bound_compare(),
        # ISSUE 13: async input pipeline — data_wait share of the timed
        # eager loop (acceptance < 5% with prefetch on)
        "prefetch": prefetch_report,
        "retrace_budget": EAGER_RETRACE_BUDGET,
        "retraces_over_budget": bool(retraces > EAGER_RETRACE_BUDGET),
        # ISSUE 8: per-phase step breakdown + measured span overhead
        "telemetry": telemetry_report,
        # ISSUE 10: per-program compile-cost/memory table + the roll-up
        # tools/bench_compare.py appends to BENCH_HISTORY.jsonl and gates
        "census": census,
        # ISSUE 14: sharded-training lane (None unless --mesh/MX_MESH_AXES)
        "sharded": sharded,
    }))


def _sharded_lane(layers=4, hidden=256, batch=32, steps=3):
    """The --mesh lane (ISSUE 14): a SpecLayout-sharded CompiledStep on
    the MX_BENCH_MESH mesh vs its replicated twin — reporting the
    buffer-census per-chip params+optimizer bytes (the series
    tools/bench_compare.py gates, keyed by mesh class) and the sharded
    step's throughput.  Returns None when no mesh is configured (the
    default eager lane is unchanged)."""
    import gc as _gc
    mesh_text = os.environ.get("MX_BENCH_MESH") or ""
    if not mesh_text:
        return None
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, programs
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import SpecLayout, make_mesh
    from mxnet_tpu.parallel.speclayout import parse_mesh_axes

    axes, sizes = parse_mesh_axes(mesh_text)
    mesh = make_mesh(axes=axes, shape=sizes, devices=jax.devices())
    layout = SpecLayout.infer(mesh)
    mesh_class = ",".join("%s=%d" % (a, s)
                          for a, s in dict(mesh.shape).items())

    rng = np.random.RandomState(0)
    X = rng.randn(batch, 64).astype(np.float32)
    Y = rng.randn(batch, 8).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    def _census_delta(lay):
        """(per-chip params+optimizer bytes, images/sec) of one fresh
        trainer under `lay`, as a buffer-census delta around its
        lifetime — the same attribution buffer_census() reports."""
        _gc.collect()
        before = programs.buffer_census()
        mx.random.seed(0)
        net = nn.Sequential()
        in_units = 64
        for _ in range(layers):
            net.add(nn.Dense(hidden, in_units=in_units,
                             activation="relu"))
            in_units = hidden
        net.add(nn.Dense(8, in_units=in_units))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(list(net.collect_params().values()), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        step = tr.make_compiled_step(net, loss_fn, layout=lay)
        step.step(nd.array(X), nd.array(Y), batch_size=batch)   # trace
        step.step(nd.array(X), nd.array(Y), batch_size=batch)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step.step(nd.array(X), nd.array(Y), batch_size=batch)
        jax.block_until_ready(loss._jax)
        ips = batch * steps / (time.perf_counter() - t0)
        _gc.collect()
        after = programs.buffer_census()

        def delta(owner):
            return max(0, after[owner]["bytes_per_chip"]
                       - before[owner]["bytes_per_chip"])
        return delta("params"), delta("optimizer_state"), ips

    repl_params, repl_opt, repl_ips = _census_delta(None)
    sh_params, sh_opt, sh_ips = _census_delta(layout)
    fsdp = layout.fsdp
    measured = (repl_params + repl_opt) / max(1, sh_params + sh_opt)
    return {
        "mesh": mesh_text,
        "mesh_class": mesh_class,
        "fsdp": fsdp,
        "params_bytes_per_chip": sh_params,
        "optimizer_bytes_per_chip": sh_opt,
        "replicated_params_bytes": repl_params,
        "replicated_optimizer_bytes": repl_opt,
        # per-chip state must drop ~linearly with the fsdp axis
        "ideal_ratio": fsdp,
        "measured_ratio": round(measured, 3),
        "within_15pct_of_ideal": bool(measured >= 0.85 * fsdp),
        "images_per_sec": round(sh_ips, 2),
        "replicated_images_per_sec": round(repl_ips, 2),
    }


def _telemetry_overhead(layers=8, hidden=64, batch=16, pairs=12):
    """Measured cost of the telemetry span/record layer on an eager
    training step (ISSUE 8 acceptance: <= 5%).

    Alternating off/on step pairs with best-of-N per mode: the layer's
    cost is deterministic (a few dict ops + leaf-lock bumps per phase),
    so it survives the min, while interleaving cancels clock-speed and
    scheduler drift that a two-block comparison would misread as
    overhead.  The probe uses a fast MLP step — on CPU a resnet step
    takes seconds and its run-to-run noise alone dwarfs the microsecond
    span cost being measured."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.base import set_env
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.Sequential()
    in_units = 32
    for _ in range(layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(8, in_units=in_units))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(list(net.collect_params().values()), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(batch, 32).astype(np.float32))
    y = nd.array(rng.randn(batch, 8).astype(np.float32))

    def one_step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        tr.step(batch_size=batch)
        jax.block_until_ready(loss._jax)

    one_step()
    one_step()                          # warm: compile + state creation
    times = {"0": [], "1": []}
    prev = os.environ.get("MX_TELEMETRY")
    try:
        for _ in range(pairs):
            for mode in ("0", "1"):
                set_env("MX_TELEMETRY", mode)
                t0 = time.perf_counter()
                one_step()
                times[mode].append(time.perf_counter() - t0)
    finally:
        set_env("MX_TELEMETRY", prev if prev is not None else "1")
    # per-pair differences cancel slow machine drift; their median is
    # robust to the occasional preempted step either side
    deltas = sorted(on - off for on, off in zip(times["1"], times["0"]))
    med_delta = deltas[len(deltas) // 2]
    t_off, t_on = min(times["0"]), min(times["1"])
    return {
        "workload": "mlp%dx%d_eager_step" % (layers, hidden),
        "pairs": pairs,
        "step_ms_telemetry_off": round(t_off * 1e3, 4),
        "step_ms_telemetry_on": round(t_on * 1e3, 4),
        "overhead_pct": round(max(0.0, med_delta / t_off * 100.0), 2),
    }


def _dispatch_bound_compare(layers=24, hidden=64, batch=16, steps=8):
    """The step-time win whole-step compilation buys where per-dispatch
    host overhead dominates (deep narrow MLP, per-op eager forward — the
    non-hybridized Gluon debug pipeline — vs ONE scanned window).  On a
    tunnel-attached TPU the resnet lane itself is dispatch-bound; on the
    CPU smoke this sub-benchmark is the honest proxy for that regime."""
    import jax
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    def build():
        mx.random.seed(0)
        net = nn.Sequential()
        in_units = 32
        for _ in range(layers):
            net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
            in_units = hidden
        net.add(nn.Dense(8, in_units=in_units))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(list(net.collect_params().values()), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        return net, tr

    rng = np.random.RandomState(0)
    X = rng.randn(batch, 32).astype(np.float32)
    Y = rng.randn(batch, 8).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    net_e, tr_e = build()
    x, y = nd.array(X), nd.array(Y)

    def eager_step():
        with autograd.record():
            loss = loss_fn(net_e(x), y)
        loss.backward()
        tr_e.step(batch_size=batch)
        return loss
    eager_step()
    jax.block_until_ready(eager_step()._jax)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = eager_step()
    jax.block_until_ready(loss._jax)
    eager_sps = steps / (time.perf_counter() - t0)

    net_c, tr_c = build()
    cstep = tr_c.make_compiled_step(net_c, loss_fn)
    Xw = np.broadcast_to(X, (steps,) + X.shape).copy()
    Yw = np.broadcast_to(Y, (steps,) + Y.shape).copy()
    cstep.run_window(Xw, Yw)            # warm: trace + compile
    t0 = time.perf_counter()
    loss = cstep.run_window(Xw, Yw)
    jax.block_until_ready(loss._jax)
    compiled_sps = steps / (time.perf_counter() - t0)
    return {
        "model": "mlp%dx%d" % (layers, hidden),
        "eager_steps_per_sec": round(eager_sps, 2),
        "compiled_steps_per_sec": round(compiled_sps, 2),
        "scan_window": steps,
        "speedup_compiled_vs_eager": round(compiled_sps / eager_sps, 2),
    }


def run_exchange_bench():
    """--exchange: bucketed gradient-exchange micro-bench (ISSUE 5).

    Times one batched push+pull of a ResNet-ish key set (many small dense
    tensors + a few large ones) through the collective store per wire
    mode — fp32, bf16 cast, int8 per-block quantized, 2-bit — and reports
    ms/step plus the measured wire bytes (engine.wire_bytes deltas).  On
    one process the collective is local, so this isolates the quantize/
    bucketing overhead the compression pays for its bandwidth win; on a
    real pod the same harness times the ICI/DCN exchange itself.
    """
    import jax
    if os.environ.get("MX_BENCH_PLATFORM") == "cpu":
        from mxnet_tpu.base import pin_cpu
        pin_cpu()
    import numpy as np
    from mxnet_tpu import kvstore, nd
    from mxnet_tpu.engine import engine

    on_cpu = jax.default_backend() == "cpu"
    iters = 3 if on_cpu else 20
    rng = np.random.RandomState(0)
    # conv-net-like: many small params, a few big FC/embedding-scale ones
    sizes = [256] * 40 + [16 * 1024] * 12 + [256 * 1024] * 4 + [2 << 20]
    grads = [nd.array(rng.randn(n).astype(np.float32)) for n in sizes]
    keys = list(range(len(sizes)))
    total_mb = sum(sizes) * 4 / (1 << 20)

    per_mode = {}
    for mode in ("fp32", "bf16", "int8", "2bit"):
        kv = kvstore.create("ici")
        if mode != "fp32":
            kv.set_gradient_compression({"type": mode})
        for k, g in zip(keys, grads):
            kv.init(k, nd.zeros((g.size,)))
        vlists = [[g] for g in grads]
        kv.push(keys, vlists)                       # warm (compile)
        kv.pull(keys, vlists)
        grads[0].wait_to_read()
        w0 = engine.snapshot()["wire_bytes"]
        t0 = time.perf_counter()
        for _ in range(iters):
            kv.push(keys, vlists)
            kv.pull(keys, vlists)
        grads[0].wait_to_read()
        dt = time.perf_counter() - t0
        wire_mb = (engine.snapshot()["wire_bytes"] - w0) / iters / (1 << 20)
        per_mode[mode] = {"ms_per_step": round(dt / iters * 1e3, 2),
                          "wire_mb_per_step": round(wire_mb, 3)}
    fp32_mb = per_mode["fp32"]["wire_mb_per_step"]
    for mode, rec in per_mode.items():
        rec["wire_reduction_vs_fp32"] = round(
            fp32_mb / max(1e-9, rec["wire_mb_per_step"]), 2)
    print(json.dumps({
        "metric": "gradient_exchange_wire_reduction_int8",
        "value": per_mode["int8"]["wire_reduction_vs_fp32"],
        "unit": "x_fewer_bytes",
        "device": jax.default_backend(),
        "keys": len(sizes),
        "payload_mb": round(total_mb, 1),
        "iters": iters,
        "per_mode": per_mode,
    }))


def run_score_bench():
    """--score: model-zoo INFERENCE throughput vs batch size (reference:
    example/image-classification/benchmark_score.py).  Hybridized forward
    (one executable per shape), bf16."""
    import jax
    if os.environ.get("MX_BENCH_PLATFORM") == "cpu":
        from mxnet_tpu.base import pin_cpu
        pin_cpu()
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    on_cpu = jax.default_backend() == "cpu"
    # compute must actually LIVE on the accelerator: default ctx is cpu(0),
    # which would silently benchmark XLA:CPU under a TPU label
    ctx = mx.cpu(0) if on_cpu else mx.tpu(0)
    models = ["resnet18_v1"] if on_cpu else \
        ["resnet18_v1", "resnet50_v1", "mobilenet1_0"]
    batches = [1, 8] if on_cpu else [1, 8, 32, 128]
    size = 64 if on_cpu else 224
    iters = 3 if on_cpu else 20
    results = {}
    mx.random.seed(0)
    np.random.seed(0)
    for name in models:
        net = getattr(vision, name)(classes=1000)
        net.initialize(mx.init.Xavier(), ctx=ctx)
        net.cast("bfloat16")
        net.hybridize(static_alloc=True)
        per_batch = {}
        for b in batches:
            x = mx.nd.array(np.random.rand(b, 3, size, size),
                            dtype="bfloat16", ctx=ctx)
            net(x).wait_to_read()                  # compile
            t0 = time.perf_counter()
            for _ in range(iters):
                out = net(x)
            out.wait_to_read()
            per_batch[b] = round(b * iters /
                                 (time.perf_counter() - t0), 2)
        results[name] = per_batch
    top = results[models[0]][batches[-1]]
    print(json.dumps({
        "metric": "model_zoo_inference_images_per_sec",
        "value": top, "unit": "images/sec",
        "vs_baseline": 0.0, "device": jax.default_backend(),
        "per_model": results,
    }))


def _pctile(sorted_vals, p):
    """p-th percentile of an ascending list (truncation-indexed — the
    convention both serve lanes share); 0.0 on empty."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p / 100.0 * len(sorted_vals)))]


def run_serve_bench(rate=None, duration=None, senders=12,
                    routed=False):
    """--serve: open-loop load against a REAL local serving replica
    (ISSUE 9 acceptance lane).  ``routed=True`` (``--serve --routed``,
    ISSUE 17) appends a paired direct-vs-through-the-router probe
    against the SAME warm replica — interleaved closed-loop lanes,
    medians gated at p50/p99 within 10%.

    A synthetic Poisson arrival process (configurable rate/duration;
    open-loop: the schedule never slows down for the server, so queueing
    shows up as latency, not as a lower offered rate) drives PREDICT
    RPCs over a real socket through the SEQ envelope into the
    micro-batcher.  Reports p50/p99 end-to-end latency (measured from
    the SCHEDULED arrival, so sender lateness counts — the
    coordinated-omission-safe convention), achieved throughput, the
    batch-occupancy histogram, the rejection rate, and the serve-time
    retrace count after warmup (must be 0: every dispatch must hit the
    AOT bucket table).
    """
    import socket as _socket
    import threading
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import (Overloaded, ServeClient, ServeServer,
                                 Servable, serve_forever)
    from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example

    rate = float(rate or os.environ.get("MX_BENCH_SERVE_RATE", 250.0))
    duration = float(duration or
                     os.environ.get("MX_BENCH_SERVE_DURATION", 2.0))

    s = _socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    state = ServeServer()
    state.host.deploy(Servable(demo_block(), name="demo-mlp", version=1),
                      example=demo_example())
    stop_ev = threading.Event()
    threading.Thread(target=serve_forever,
                     kwargs=dict(port=port, state=state,
                                 stop_event=stop_ev),
                     daemon=True).start()
    addr = "127.0.0.1:%d" % port
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            _socket.create_connection(("127.0.0.1", port),
                                      timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)

    # a couple of warm round-trips (client connect, codec, first batch)
    warm_cli = ServeClient([addr], timeout=30)
    xw = np.zeros((1, DEMO_IN), np.float32)
    for _ in range(3):
        warm_cli.predict([xw])
    warm_cli.close()
    sv = state.host.active()
    retraces_before = sv.retraces
    reg = telemetry.registry
    rej0 = reg.value("serve.rejected")
    batches0 = reg.value("serve.batches")
    occ_inst = reg.find("serve.batch_occupancy")
    occ0 = occ_inst.snapshot() if occ_inst is not None else None

    # open-loop schedule: Poisson arrivals, single-row requests
    rng = np.random.RandomState(7)
    arrivals = np.cumsum(rng.exponential(1.0 / rate,
                                         int(rate * duration) + 1))
    arrivals = arrivals[arrivals < duration]
    payloads = [rng.randn(1, DEMO_IN).astype(np.float32)
                for _ in range(len(arrivals))]
    latencies, rejected, errors = [], [0], [0]
    lat_lock = threading.Lock()
    next_i = [0]
    idx_lock = threading.Lock()
    t0 = time.perf_counter()

    def sender():
        cli = ServeClient([addr], timeout=30)
        while True:
            with idx_lock:
                i = next_i[0]
                if i >= len(arrivals):
                    break
                next_i[0] += 1
            due = t0 + arrivals[i]
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                cli.predict([payloads[i]])
                lat = time.perf_counter() - due
                with lat_lock:
                    latencies.append(lat)
            except Overloaded:
                with lat_lock:
                    rejected[0] += 1
            except Exception:
                with lat_lock:
                    errors[0] += 1
        cli.close()

    threads = [threading.Thread(target=sender, daemon=True)
               for _ in range(senders)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration + 60)
    wall = time.perf_counter() - t0

    # report the LOAD's occupancy only — the warm-up round-trips above
    # also dispatched (single-row) batches; deltas keep them out, like
    # rejected_counter / retraces_after_warmup below
    occupancy = {}
    inst = reg.find("serve.batch_occupancy")
    if inst is not None:
        snap = inst.snapshot()
        count = snap["count"] - (occ0["count"] if occ0 else 0)
        total = snap["sum"] - (occ0["sum"] if occ0 else 0.0)
        occupancy = {
            "count": count,
            "avg_rows": round(total / count, 2) if count else 0.0,
            "max_rows": snap["max"],
            "buckets": {le: c - (occ0["buckets"].get(le, 0)
                                 if occ0 else 0)
                        for le, c in snap["buckets"].items()}}
    lat_ms = sorted(l * 1e3 for l in latencies)

    def pct(p):
        return round(_pctile(lat_ms, p), 3)

    n_ok = len(latencies)
    report = {
        "metric": "serve_demo_requests_per_sec",
        "value": round(n_ok / wall, 2),
        "unit": "requests/sec",
        "device": "cpu" if os.environ.get("MX_FORCE_CPU") else "default",
        "offered_rate": rate,
        "duration_s": duration,
        "requests": len(arrivals),
        "completed": n_ok,
        "rejected": rejected[0],
        "errors": errors[0],
        "rejection_rate": round(rejected[0] / max(1, len(arrivals)), 4),
        "latency_ms": {"p50": pct(50), "p90": pct(90), "p99": pct(99),
                       "max": round(lat_ms[-1], 3) if lat_ms else 0.0},
        "batch_occupancy": occupancy,
        "batches": reg.value("serve.batches") - batches0,
        "retraces_after_warmup": sv.retraces - retraces_before,
        "zero_serve_time_retraces": sv.retraces == retraces_before,
        "rejected_counter": reg.value("serve.rejected") - rej0,
        "phases": {k: v for k, v in telemetry.phase_snapshot().items()
                   if k in ("queue_wait", "pad", "serve_dispatch",
                            "scatter")},
        # ISSUE 10: the serve lane's program census — every bucket
        # program with compile time and (where the backend provides it)
        # memory/cost metadata
        "census": _census_report(),
    }

    # fleet-collector overhead probe (ISSUE 12 acceptance): INTERLEAVED
    # paired closed-loop lanes against the SAME warm replica — each
    # cycle runs a collector-off lane then a collector-on lane, and the
    # gate compares the MEDIANS (interleaving cancels box drift, the
    # median kills one-off scheduler spikes).  Expected <=2%, gate <=5%
    # on BOTH throughput and p99.  The collector runs in a SUBPROCESS,
    # matching the production topology (the supervisor hosts it): what
    # lands on the replica is exactly the per-scrape METRICS handling
    # (registry snapshot + one socket round-trip), not the collector's
    # own merge loop stealing the GIL.
    from mxnet_tpu.base import get_env as _get_env

    def _probe_load(nreq, rate_, target=addr):
        cli = ServeClient([target], timeout=30)
        lat = []
        sched = np.cumsum(rng.exponential(1.0 / rate_, nreq))
        t0p = time.perf_counter()
        for i in range(nreq):
            due = t0p + sched[i]
            d = due - time.perf_counter()
            if d > 0:
                time.sleep(d)
            try:
                cli.predict([payloads[i % len(payloads)]])
                lat.append(time.perf_counter() - due)
            except Exception:
                pass        # shed/failed probes just shrink the sample
        cli.close()
        wallp = time.perf_counter() - t0p
        lat.sort()
        p50_ = lat[min(len(lat) - 1, int(0.50 * len(lat)))] * 1e3 \
            if lat else 0.0
        p99_ = lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3 \
            if lat else 0.0
        # plain floats: the latencies are contaminated with np.float64
        # via the np.cumsum schedule, and a np.bool_ gate comparison
        # would fail json.dumps
        return float(len(lat) / wallp), float(p50_), float(p99_)

    probe_rate = max(50.0, rate / 2.0)
    fleet_interval = _get_env("MX_FLEET_INTERVAL", 2.0, float) or 2.0
    # span >= 3 scrape rounds per lane so the paired delta actually
    # contains scrapes (bounded so the probe stays a bench, not a soak)
    probe_n = int(os.environ.get(
        "MX_BENCH_FLEET_PROBE",
        max(200, int(probe_rate * min(3.0 * fleet_interval, 8.0)))))

    # (a) deterministic per-scrape cost: time the METRICS round-trip
    # the collector performs; the replica-side duty cycle it implies
    # (scrape_ms / interval_ms, an upper bound — it charges the whole
    # round-trip as stolen replica CPU) is the gated number, because
    # sub-5% paired deltas sit below a shared box's noise floor.
    from mxnet_tpu import fleet as _fleet
    scrape_ms = []
    for _ in range(5):
        t0s = time.perf_counter()
        _fleet.fetch_metrics(addr, fmt="json")
        scrape_ms.append((time.perf_counter() - t0s) * 1e3)
    scrape_ms = sorted(scrape_ms)[len(scrape_ms) // 2]
    modeled_pct = 100.0 * (scrape_ms / 1e3) / fleet_interval

    # (b) interleaved paired lanes at the CONFIGURED MX_FLEET_INTERVAL;
    # the collector subprocess is spawned fresh per on-lane so the
    # adjacent off-lane is genuinely collector-free
    probe_src = (
        "import sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from mxnet_tpu import fleet\n"
        "c = fleet.FleetCollector([fleet.FleetMember('serve', 0, "
        "addr=%r)], interval=%r)\n"
        "c.scrape_once()\n"
        "print('SCRAPING', flush=True)\n"
        "c.start()\n"
        "time.sleep(600)\n" % (os.path.dirname(os.path.abspath(__file__)),
                               addr, fleet_interval))
    cycles = int(os.environ.get("MX_BENCH_FLEET_CYCLES", 3))
    off_tps, off_p99s, on_tps, on_p99s = [], [], [], []
    for _cycle in range(cycles):
        tp_, _p50, p99_ = _probe_load(probe_n, probe_rate)
        off_tps.append(tp_)
        off_p99s.append(p99_)
        proc = subprocess.Popen([sys.executable, "-c", probe_src],
                                stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()   # first scrape completed
            if "SCRAPING" not in line:
                raise RuntimeError(
                    "fleet probe collector failed to start")
            # settle past the subprocess's interpreter+import CPU burst:
            # production collectors start ONCE — the steady state under
            # measurement is scraping, not python startup sharing the
            # box with the replica for the lane's first second
            time.sleep(0.75)
            tp_, _p50, p99_ = _probe_load(probe_n, probe_rate)
            on_tps.append(tp_)
            on_p99s.append(p99_)
        finally:
            proc.kill()
            proc.wait()

    def _median(vs):
        return sorted(vs)[len(vs) // 2] if vs else 0.0

    off_tp, off_p99 = _median(off_tps), _median(off_p99s)
    on_tp, on_p99 = _median(on_tps), _median(on_p99s)
    tp_overhead = 100.0 * (off_tp - on_tp) / off_tp if off_tp else 0.0
    p99_overhead = 100.0 * (on_p99 - off_p99) / off_p99 if off_p99 \
        else 0.0
    report["fleet_collector"] = {
        "scrape_interval_s": fleet_interval,
        "collector": "subprocess (supervisor topology)",
        "cycles": cycles,
        "scrape_roundtrip_ms": round(scrape_ms, 3),
        "modeled_overhead_pct": round(modeled_pct, 3),
        "throughput_off_rps": round(off_tp, 2),
        "throughput_on_rps": round(on_tp, 2),
        "p99_off_ms": round(off_p99, 3),
        "p99_on_ms": round(on_p99, 3),
        "throughput_overhead_pct": round(tp_overhead, 2),
        "p99_overhead_pct": round(p99_overhead, 2),
        "gate_pct": 5.0,
        # the ISSUE acceptance gate, measured: median-of-interleaved
        # throughput AND p99 deltas <=5% (negative deltas = noise
        # favoring the collector-on lanes); the modeled duty cycle
        # rides along as the deterministic cross-check
        "within_gate": tp_overhead <= 5.0 and p99_overhead <= 5.0,
    }

    if routed:
        # ISSUE 17 acceptance: the session router's forwarding tax.
        # A SUBPROCESS router fronts the SAME warm replica — the
        # production topology (the supervisor runs the router as its
        # own process), and the same reasoning as the collector probe
        # above: in-process it would fight the replica's batcher for
        # the GIL and charge scheduler contention to forwarding.
        # Interleaved paired closed-loop lanes (direct, then through
        # the router) per cycle cancel box drift, medians kill
        # scheduler spikes.  Gate: routed p50 AND p99 within 10% of
        # direct, with an ABSOLUTE ms floor — on a fast box a 10%
        # relative delta of a small p50 is two-loopback-hop noise, so
        # the gate also passes when the ADDED latency is under the
        # floor flat.
        rs = _socket.socket()
        rs.bind(("", 0))
        rport_ = rs.getsockname()[1]
        rs.close()
        renv = dict(os.environ, JAX_PLATFORMS="cpu", MX_FORCE_CPU="1")
        renv["PYTHONPATH"] = (
            os.path.dirname(os.path.abspath(__file__))
            + os.pathsep + renv.get("PYTHONPATH", ""))
        rproc = subprocess.Popen(
            [sys.executable, "-m", "mxnet_tpu.serve.router",
             "--port", str(rport_), "--replicas", addr],
            env=renv, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        raddr = "127.0.0.1:%d" % rport_
        rdeadline = time.monotonic() + 30
        while time.monotonic() < rdeadline:
            try:
                _socket.create_connection(("127.0.0.1", rport_),
                                          timeout=0.2).close()
                break
            except OSError:
                time.sleep(0.05)
        warm_r = ServeClient([raddr], timeout=30)
        for _ in range(3):
            warm_r.predict([xw])
        warm_r.close()
        # probe BELOW the queueing knee: at the fleet probe's rate the
        # single closed-loop sender sits near 50% utilization, where
        # open-loop lateness cascades amplify ANY per-request delta
        # into the tail — that measures the queue, not the router.  At
        # ~5x the service time between arrivals the latency IS the
        # path: replica service + (routed) two loopback hops.
        routed_rate = min(probe_rate, 50.0)
        routed_n = max(120, int(3.0 * routed_rate))
        d_tps, d_p50s, d_p99s = [], [], []
        r_tps, r_p50s, r_p99s = [], [], []
        for _cycle in range(cycles):
            tp_, p50_, p99_ = _probe_load(routed_n, routed_rate)
            d_tps.append(tp_)
            d_p50s.append(p50_)
            d_p99s.append(p99_)
            tp_, p50_, p99_ = _probe_load(routed_n, routed_rate,
                                          target=raddr)
            r_tps.append(tp_)
            r_p50s.append(p50_)
            r_p99s.append(p99_)
        rproc.kill()
        rproc.wait()
        d_p50, d_p99 = _median(d_p50s), _median(d_p99s)
        r_p50, r_p99 = _median(r_p50s), _median(r_p99s)
        p50_pct = 100.0 * (r_p50 - d_p50) / d_p50 if d_p50 else 0.0
        p99_pct = 100.0 * (r_p99 - d_p99) / d_p99 if d_p99 else 0.0
        gate_pct, floor_ms = 10.0, 1.0
        report["routed"] = {
            "cycles": cycles,
            "probe_rate": routed_rate,
            "probe_requests": routed_n,
            "throughput_direct_rps": round(_median(d_tps), 2),
            "throughput_routed_rps": round(_median(r_tps), 2),
            "p50_direct_ms": round(d_p50, 3),
            "p50_routed_ms": round(r_p50, 3),
            "p99_direct_ms": round(d_p99, 3),
            "p99_routed_ms": round(r_p99, 3),
            "p50_overhead_pct": round(p50_pct, 2),
            "p99_overhead_pct": round(p99_pct, 2),
            "gate_pct": gate_pct,
            "floor_ms": floor_ms,
            "within_gate": bool(
                (p50_pct <= gate_pct or r_p50 - d_p50 <= floor_ms)
                and (p99_pct <= gate_pct or r_p99 - d_p99 <= floor_ms)),
        }
    stop_ev.set()
    print(json.dumps(report))


def run_decode_bench(n_gens=None, rate=None):
    """--serve --decode: the autoregressive decode lane (ISSUE 15
    acceptance).

    A mixed-length Poisson workload (70% short generations, 30% long
    ones — the regime where request-level batching starves) drives the
    continuous-batching decode engine in-process, then the IDENTICAL
    workload replays against ``mode="request"`` (admit a batch, run it
    to completion — the classic strawman).  The offered rate
    deliberately exceeds single-replica capacity so a backlog forms:
    throughput then measures the ENGINE's batching discipline, not the
    arrival process (an underloaded engine drains any schedule at the
    offered rate and the comparison degenerates to 1x).  Reports
    tokens/sec,
    per-token p50/p99 (first token = submit→harvest including queue +
    prefill; then inter-token gaps), slot occupancy, the
    continuous-vs-request speedup (acceptance >= 2x), zero serve-time
    retraces and FLAT KV-pool bytes across the whole run (the pool is
    donated through every step — any growth is a leak).

    ISSUE 18 adds three PAGED lanes on top:

    * ``paged`` — the identical mixed workload through the paged
      engine (same geometry, auto ``kv_pages`` == the flat pool's
      HBM): tokens must match the flat continuous lane ELEMENT-WISE,
      the page heap must stay flat, and warm retraces must stay zero;
    * ``shared_prefix`` — N sessions over K long shared prompts:
      flat re-prefills every repeat, the paged engine answers it with
      a CoW fork + ONE replay chunk, so repeat first-token p50 must
      drop >= 5x (same tokens out of both engines);
    * ``admission`` — the census-pinned equal-HBM capacity story:
      at byte-identical KV pools the paged heap runs the mixed-length
      admission >= 4x as wide as flat slots allow.

    ISSUE 20 adds the SPECULATIVE lane: the draft-friendly demo LM
    (a deep target whose step is KV-gather-bound over a long paged
    extent, plus its 1-layer draft prefix) decodes the identical
    closed-loop workload through the plain paged engine and through
    the speculative engine (k draft dispatches + ONE k+1-position
    verify dispatch per window).  Request-level tokens/sec must come
    out >= 2x, tokens must match the plain paged lane ELEMENT-WISE
    (speculative greedy output is bit-identical by construction), the
    page heap must stay flat, and warm retraces must stay zero.
    """
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve.decode import (DecodeBatcher, DecodeConfig,
                                        DecodeServable,
                                        PagedDecodeBatcher,
                                        PagedDecodeServable,
                                        reference_generate)

    n_gens = int(n_gens or os.environ.get("MX_BENCH_DECODE_GENS", 200))
    rate = float(rate or os.environ.get("MX_BENCH_DECODE_RATE", 2500.0))
    short_new, long_new, long_frac = 2, 48, 0.3

    # the demo LM is sized so a decode step is DISPATCH-overhead-bound
    # (per-step cost ~flat in the active count) — the regime a real TPU
    # decode step lives in (weight-load-bandwidth-bound, equally
    # batch-size-invariant), where tokens-per-step translates directly
    # to throughput.  A compute-bound toy would under-credit continuous
    # batching for an artifact of the CPU bench box.
    cfg = DecodeConfig(dim=8, heads=1, layers=6, slots=8, max_tokens=48,
                       prompt_buckets=(4, 8))
    rng = np.random.RandomState(11)
    prompts = [list(map(int, rng.randint(2, cfg.vocab,
                                         size=rng.randint(2, 8))))
               for _ in range(n_gens)]
    max_news = [long_new if rng.rand() < long_frac else short_new
                for _ in range(n_gens)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_gens))
    reg = telemetry.registry

    def pct(sorted_secs, p):
        return round(_pctile(sorted_secs, p) * 1e3, 3)

    def run_lane(mode, paged=False):
        if paged:
            sv = PagedDecodeServable(config=cfg)
            eng = PagedDecodeBatcher(sv, queue_cap=n_gens + 64,
                                     mode=mode)
        else:
            sv = DecodeServable(config=cfg)
            eng = DecodeBatcher(sv, queue_cap=n_gens + 64, mode=mode)
        # untimed pre-burst: each lane measures its STEADY state, not
        # the process's first-touch costs (XLA autotune, allocator
        # warm, CPU boost ramp) — without this the lane that happens to
        # run first eats them and the comparison drifts run to run
        pre = [eng.submit([3, 4], max_new=12) for _ in range(24)]
        for g in pre:
            g.result(timeout=120)
        kv0 = sv.kv_state_bytes()
        retr0 = sv.retraces
        steps0 = reg.value("serve.decode.steps")
        gens = []
        t0 = time.perf_counter()
        # open-loop Poisson arrivals: the schedule never slows down for
        # the engine, so queueing shows up as latency
        for i in range(n_gens):
            due = t0 + arrivals[i]
            d = due - time.perf_counter()
            if d > 0:
                time.sleep(d)
            gens.append(eng.submit(prompts[i], max_new=max_news[i]))
        outs = [g.result(timeout=300) for g in gens]
        wall = time.perf_counter() - t0
        tokens = sum(len(o) for o in outs)
        steps = reg.value("serve.decode.steps") - steps0
        decode_tokens = tokens - n_gens     # first tokens = prefill
        token_lats = sorted(t for g in gens for t in g.token_times[1:])
        first_lats = sorted(g.token_times[0] for g in gens
                            if g.token_times)
        kv_flat = sv.kv_state_bytes() == kv0
        lane = {
            "mode": "paged" if paged else mode,
            "generations": n_gens,
            "tokens": tokens,
            "wall_s": round(wall, 3),
            "tokens_per_sec": round(tokens / wall, 2),
            "decode_steps": steps,
            "mean_occupancy": round(decode_tokens / steps, 2)
            if steps else 0.0,
            "token_latency_ms": {"p50": pct(token_lats, 50),
                                 "p99": pct(token_lats, 99)},
            "first_token_ms": {"p50": pct(first_lats, 50),
                               "p99": pct(first_lats, 99)},
            "retraces_after_warmup": sv.retraces - retr0,
            "kv_pool_bytes": sv.kv_state_bytes(),
            "kv_pool_flat": kv_flat,
        }
        if paged:
            lane["page_stats"] = eng.page_stats()
        eng.close()
        return lane, outs

    def run_shared_prefix_bench():
        # the prefix-reuse headline: K long shared prompts, repeats
        # must come back with ONE replay chunk under the paged engine
        # while flat pays the whole monolithic prefill again.  Model
        # sized so the prompt prefill is COMPUTE-bound (a chunk is
        # ~1/16th of it) — a dispatch-bound toy would hide the win.
        sp_len = int(os.environ.get("MX_BENCH_SHARED_LEN", 1024))
        sp_k = int(os.environ.get("MX_BENCH_SHARED_PROMPTS", 4))
        sp_reqs = int(os.environ.get("MX_BENCH_SHARED_REQS", 24))
        sp_new = 8
        base = dict(dim=32, heads=2, layers=6, slots=4, max_tokens=16,
                    prompt_buckets=(8, sp_len))
        srng = np.random.RandomState(18)
        probe = DecodeConfig(**base)
        bases = [[int(t) for t in srng.randint(2, probe.vocab,
                                               size=sp_len)]
                 for _ in range(sp_k)]
        warm_p = [int(t) for t in srng.randint(2, probe.vocab,
                                               size=sp_len)]

        def lane(paged):
            if paged:
                scfg = DecodeConfig(kv_page_len=64, prefill_chunk=64,
                                    kv_pages=128, **base)
                sv = PagedDecodeServable(config=scfg)
                eng = PagedDecodeBatcher(sv)
            else:
                sv = DecodeServable(config=DecodeConfig(**base))
                eng = DecodeBatcher(sv)
            # untimed warm generation off a DISTINCT full-length
            # prompt: compile + first-touch costs never land in the
            # first measured cold request
            eng.submit(warm_p, max_new=2).result(timeout=600)
            firsts = {"cold": [], "shared": []}
            outs, seen = [], set()
            for i in range(sp_reqs):
                k = i % sp_k
                bucket = "shared" if k in seen else "cold"
                seen.add(k)
                g = eng.submit(bases[k], max_new=sp_new)
                outs.append(g.result(timeout=600))
                firsts[bucket].append(g.token_times[0])
            stats = eng.page_stats()
            eng.close()
            ms = {b: {"p50": pct(sorted(v), 50),
                      "p99": pct(sorted(v), 99), "n": len(v)}
                  for b, v in firsts.items() if v}
            return ms, outs, stats

        flat_ms, flat_outs, _ = lane(paged=False)
        paged_ms, paged_outs, pstats = lane(paged=True)
        sp_speed = (flat_ms["shared"]["p50"]
                    / max(1e-9, paged_ms["shared"]["p50"]))
        return {
            "prompt_len": sp_len,
            "prompts": sp_k,
            "requests": sp_reqs,
            "flat_first_token_ms": flat_ms,
            "paged_first_token_ms": paged_ms,
            "shared_page_hits": pstats["shared_hits"],
            "parity": bool(paged_outs == flat_outs),
            "first_token_speedup": round(sp_speed, 2),
            "speedup_ok": bool(sp_speed >= 5.0
                               and paged_outs == flat_outs),
        }

    def run_admission_bench():
        # census-pinned equal-HBM capacity: flat slots=2 admits 2,
        # the byte-identical page heap runs the mixed-length set 6x
        # as wide (short sessions hold 1 page, not a flat extent)
        abase = dict(dim=8, heads=1, layers=1, max_tokens=16,
                     prompt_buckets=(4, 64))
        flat_cfg = DecodeConfig(slots=2, **abase)
        paged_cfg = DecodeConfig(slots=12, kv_page_len=16, kv_pages=18,
                                 **abase)
        sv = PagedDecodeServable(config=paged_cfg)
        flat_pool = (flat_cfg.layers * 2 * (flat_cfg.slots + 1)
                     * flat_cfg.max_len * flat_cfg.dim * 4)
        paged_pool = sv.page_bytes() * paged_cfg.kv_pages
        eng = PagedDecodeBatcher(sv, autostart=False)
        long_p = [int(t) for t in np.arange(64) % 7 + 1]
        work = [(long_p, 16)] + [([1 + i % 5, 2, 3, 4], 2)
                                 for i in range(11)]
        gens = [eng.submit(p, max_new=n) for p, n in work]
        eng.step_sync()                  # admission is one boundary
        concurrent = eng.active_count()
        eng.drain_sync()
        correct = all(
            g.tokens_so_far() == reference_generate(
                p, n, params=sv.params, config=paged_cfg)
            for g, (p, n) in zip(gens, work))
        eng.close()
        ratio = concurrent / float(flat_cfg.slots)
        return {
            "flat_slots": flat_cfg.slots,
            "paged_sessions": concurrent,
            "capacity_ratio": round(ratio, 2),
            "kv_pool_bytes_flat": flat_pool,
            "kv_pool_bytes_paged": paged_pool,
            "equal_hbm": bool(flat_pool == paged_pool),
            "tokens_correct": bool(correct),
            "ok": bool(ratio >= 4.0 and flat_pool == paged_pool
                       and correct),
        }

    def run_speculative_bench():
        # the speculative headline (ISSUE 20): the target is sized so
        # a decode step is KV-GATHER-bound — a deep model over a long
        # paged extent, the regime a real memory-bandwidth-bound TPU
        # decode step lives in — so the 1-layer draft costs ~1/24th of
        # a target step and the k+1-position verify costs ~one step
        # (the per-lane page gather is shared across window positions):
        # k committed tokens for ~2 target-steps' worth of HBM traffic.
        from mxnet_tpu.serve.decode import (DraftDecodeServable,
                                            SpeculativeDecodeBatcher,
                                            demo_spec_pair)
        sk = int(os.environ.get("MX_BENCH_SPEC_K", 8))
        s_gens = int(os.environ.get("MX_BENCH_SPEC_GENS", 8))
        s_new = int(os.environ.get("MX_BENCH_SPEC_NEW", 72))
        scfg = DecodeConfig(dim=64, heads=4, layers=24, slots=4,
                            max_tokens=1024, prompt_buckets=(8, 16),
                            kv_page_len=64, kv_pages=96,
                            prefill_chunk=16, spec_k=sk)
        tparams, dcfg, dparams = demo_spec_pair(scfg, draft_layers=1)
        srng = np.random.RandomState(20)
        sprompts = [[int(t) for t in srng.randint(2, scfg.vocab,
                                                  size=12)]
                    for _ in range(s_gens)]

        def lane(spec):
            sv = PagedDecodeServable(params=tparams, config=scfg)
            if spec:
                draft = DraftDecodeServable(params=dparams, config=dcfg,
                                            name="demo-lm-draft")
                eng = SpeculativeDecodeBatcher(sv, draft,
                                               queue_cap=s_gens + 8)
            else:
                eng = PagedDecodeBatcher(sv, queue_cap=s_gens + 8)
            for g in [eng.submit([3, 4, 5], max_new=8)
                      for _ in range(4)]:
                g.result(timeout=600)
            kv0 = sv.kv_state_bytes()
            retr0 = sv.retraces + (eng.draft.retraces if spec else 0)
            w0 = reg.value("serve.decode.spec_windows")
            d0 = reg.value("serve.decode.draft_steps")
            # closed-loop request-level throughput, min wall of two
            # measured passes: greedy decode is deterministic so both
            # passes emit identical tokens — the min isolates engine
            # cost from bench-box scheduling noise
            best, outs = None, None
            for _ in range(2):
                t0 = time.perf_counter()
                gens = [eng.submit(p, max_new=s_new) for p in sprompts]
                pass_outs = [g.result(timeout=600) for g in gens]
                wall = time.perf_counter() - t0
                if best is None or wall < best:
                    best, outs = wall, pass_outs
            tokens = sum(len(o) for o in outs)
            lane_rec = {
                "tokens": tokens,
                "wall_s": round(best, 3),
                "tokens_per_sec": round(tokens / best, 2),
                "kv_pool_flat": bool(sv.kv_state_bytes() == kv0),
                "retraces_after_warmup":
                    sv.retraces + (eng.draft.retraces if spec else 0)
                    - retr0,
            }
            if spec:
                windows = reg.value("serve.decode.spec_windows") - w0
                lane_rec["spec_windows"] = windows
                lane_rec["draft_steps"] = \
                    reg.value("serve.decode.draft_steps") - d0
                st = eng.page_stats()
                lane_rec["engine"] = st["engine"]
                lane_rec["draft_model"] = st["draft_model"]
            eng.close()
            return lane_rec, outs

        base_rec, base_outs = lane(spec=False)
        spec_rec, spec_outs = lane(spec=True)
        ratio = (spec_rec["tokens_per_sec"]
                 / max(1e-9, base_rec["tokens_per_sec"]))
        parity = bool(spec_outs == base_outs)
        return {
            "spec_k": sk,
            "target_layers": scfg.layers,
            "draft_layers": dcfg.layers,
            "kv_extent_tokens": scfg.max_tokens,
            "generations": s_gens,
            "max_new": s_new,
            "paged_baseline": base_rec,
            "speculative": spec_rec,
            "request_speedup": round(ratio, 2),
            "parity": parity,
            "kv_pool_flat": bool(base_rec["kv_pool_flat"]
                                 and spec_rec["kv_pool_flat"]),
            "zero_retraces": bool(
                base_rec["retraces_after_warmup"] == 0
                and spec_rec["retraces_after_warmup"] == 0),
            "speedup_ok": bool(ratio >= 2.0 and parity),
        }

    cont, cont_outs = run_lane("continuous")
    req, _ = run_lane("request")
    paged_lane, paged_outs = run_lane("continuous", paged=True)
    shared = run_shared_prefix_bench()
    admission = run_admission_bench()
    speculative = run_speculative_bench()
    speedup = cont["tokens_per_sec"] / max(1e-9, req["tokens_per_sec"])
    report = {
        "metric": "serve_decode_tokens_per_sec",
        "value": cont["tokens_per_sec"],
        "unit": "tokens/sec",
        "device": "cpu" if os.environ.get("MX_FORCE_CPU") else "default",
        "decode": {
            "offered_rate": rate,
            "slots": cfg.slots,
            "mix": {"short_tokens": short_new, "long_tokens": long_new,
                    "long_fraction": long_frac},
            "continuous": cont,
            "request_level": req,
            "continuous_speedup": round(speedup, 2),
            "speedup_ok": bool(speedup >= 2.0),
            "kv_pool_flat": bool(cont["kv_pool_flat"]),
            "zero_serve_time_retraces": bool(
                cont["retraces_after_warmup"] == 0
                and req["retraces_after_warmup"] == 0),
            "paged": {
                "lane": paged_lane,
                "parity_with_flat": bool(paged_outs == cont_outs),
                "kv_pool_flat": bool(paged_lane["kv_pool_flat"]),
                "zero_retraces": bool(
                    paged_lane["retraces_after_warmup"] == 0),
            },
            "shared_prefix": shared,
            "admission": admission,
            "speculative": speculative,
        },
        "phases": {k: v for k, v in telemetry.phase_snapshot().items()
                   if k in ("prefill", "decode_step", "kv_evict")},
        "census": _census_report(),
    }
    print(json.dumps(report))


def run_warm_spawn_bench():
    """--warm-spawn: serve replica ready-to-traffic time, cold vs warm
    (ISSUE 13 acceptance lane).

    Spawns the compile-heavy conv demo replica (resnet18 @ 64x64 — the
    compile-bound regime a TPU replica lives in) twice against one
    persistent compile-cache directory: the COLD spawn pays every
    bucket program's trace+XLA compile and populates the store; the
    WARM spawn deserializes the same executables.  Ready-to-traffic is
    measured spawn → first successful PREDICT over a real socket, so
    interpreter+jax import, model build, bucket warm-up and server
    bind all count.  The replica's compile-cache counters and census
    are scraped over the METRICS verb — the warm spawn must report
    cache hits == its bucket count and warm compile seconds ~0.
    """
    import shutil
    import socket as _socket
    import tempfile
    import numpy as np
    from mxnet_tpu import fleet
    from mxnet_tpu.serve import ServeClient
    from mxnet_tpu.serve.demo import DEMO_CONV_SHAPE

    cache_dir = tempfile.mkdtemp(prefix="mx_warm_spawn_cache_")
    buckets = os.environ.get("MX_BENCH_WARM_BUCKETS", "1,2,4,8,16,32,64")
    spawn_timeout = float(os.environ.get("MX_BENCH_WARM_TIMEOUT", 300))

    def _free_port():
        s = _socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn_and_measure(tag):
        port = _free_port()
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu", MX_FORCE_CPU="1",
                   MX_COMPILE_CACHE=cache_dir,
                   MX_SERVE_BUCKETS=buckets,
                   PYTHONPATH=os.path.dirname(os.path.abspath(__file__))
                   + os.pathsep + os.environ.get("PYTHONPATH", ""))
        t0 = time.perf_counter()
        # stderr goes to a FILE, not a pipe: a chatty cold compile
        # could fill a pipe buffer and deadlock the replica before it
        # ever binds — the file is read back only on failure
        err_path = os.path.join(cache_dir, "replica-%s.stderr" % tag)
        err_f = open(err_path, "wb")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.serve", "--demo-conv",
                 "--port", str(port)],
                env=env, stdout=subprocess.DEVNULL, stderr=err_f)
        finally:
            err_f.close()
        addr = "127.0.0.1:%d" % port
        x = np.zeros((1,) + DEMO_CONV_SHAPE, np.float32)
        ready_s = None
        deadline = time.monotonic() + spawn_timeout
        while time.monotonic() < deadline and proc.poll() is None:
            try:
                cli = ServeClient([addr], timeout=10)
                cli.predict([x])
                ready_s = time.perf_counter() - t0
                cli.close()
                break
            except Exception:
                time.sleep(0.05)
        if ready_s is None:
            proc.kill()
            proc.wait()
            try:
                with open(err_path, "rb") as f:
                    err = f.read()
            except OSError:
                err = b""
            raise RuntimeError("warm-spawn %s replica never became "
                               "ready: %s" % (tag,
                                              err.decode(errors="replace")
                                              [-2000:]))
        # the replica's own receipts, over the wire it serves on
        snap = fleet.fetch_metrics(addr, fmt="json")

        def _val(name):
            total = 0
            for entry in snap.values():
                if isinstance(entry, dict) and entry.get("name") == name:
                    total += int(entry.get("value", 0))
            return total

        compile_s = 0.0
        for entry in snap.values():
            if isinstance(entry, dict) and \
                    entry.get("name") == "program_compile_seconds" and \
                    entry.get("type") == "histogram":
                compile_s += float(entry.get("sum", 0.0))
        stats = {
            "ready_to_traffic_s": round(ready_s, 3),
            "cache_hits": _val("compile_cache.hits"),
            "cache_misses": _val("compile_cache.misses"),
            "cache_writes": _val("compile_cache.writes"),
            "compile_seconds_total": round(compile_s, 3),
        }
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        return stats

    try:
        cold = spawn_and_measure("cold")
        warm = spawn_and_measure("warm")
    finally:
        if not os.environ.get("MX_BENCH_WARM_KEEP"):
            shutil.rmtree(cache_dir, ignore_errors=True)
    n_buckets = len([b for b in buckets.split(",") if b.strip()])
    speedup = cold["ready_to_traffic_s"] / max(1e-9,
                                               warm["ready_to_traffic_s"])
    print(json.dumps({
        "metric": "serve_warm_spawn_speedup",
        "value": round(speedup, 2),
        "unit": "x_faster_ready_to_traffic",
        "device": "cpu",
        "buckets": buckets,
        "cold": cold,
        "warm": warm,
        "warm_spawn_seconds": warm["ready_to_traffic_s"],
        "cold_spawn_seconds": cold["ready_to_traffic_s"],
        "gate": 5.0,
        "within_gate": bool(speedup >= 5.0),
        "warm_hits_cover_buckets": bool(warm["cache_hits"] >= n_buckets),
        "warm_compile_under_1s": bool(
            warm["compile_seconds_total"] < 1.0),
    }))


def run_real_data_bench():
    """--real-data: prove the input pipeline (.rec → JPEG decode → augment →
    NCHW batch) sustains the compute rate (SURVEY hard part 7: ~3k img/s
    decode behind a saturated MXU).  Builds a synthetic ImageNet-shaped
    .rec pack, then measures ImageRecordIter throughput standalone."""
    import tempfile
    import numpy as np
    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter

    n_img, edge, batch = 512, 256, 64
    d = tempfile.mkdtemp(prefix="mxbench_rec_")
    prefix = os.path.join(d, "synth")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    # JPEG-realistic content: smooth low-freq fields, not raw noise
    base = rng.rand(8, edge, edge, 3)
    for i in range(n_img):
        img = (base[i % 8] * (120 + (i % 100)) % 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 1000), i, 0), img, quality=90))
    w.close()

    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         data_shape=(3, 224, 224), batch_size=batch,
                         shuffle=True, rand_crop=True, rand_mirror=True,
                         preprocess_threads=os.cpu_count() or 8)
    for _ in range(2):  # warm the pool
        next(it)
    it.reset()
    t0 = time.perf_counter()
    n = 0
    for b in it:
        n += b.data[0].shape[0]
    dt = time.perf_counter() - t0
    iter_ips = round(n / dt, 2)

    # DataLoader worker-model comparison on the same decode+augment work:
    # serial vs GIL-bound threads vs the reference-style spawned processes
    # (gluon/data/dataloader.py _MultiWorkerIter equivalent).
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision import ImageRecordDataset

    ds = ImageRecordDataset(prefix + ".rec",
                            transform=_ingest_decode_transform)
    n_workers = min(4, os.cpu_count() or 4)
    loader_ips = {}
    for mode, kw in (("serial", {"num_workers": 0}),
                     ("threads", {"num_workers": n_workers,
                                  "thread_pool": True}),
                     ("processes", {"num_workers": n_workers})):
        dl = DataLoader(ds, batch_size=batch, **kw)
        it2 = iter(dl)
        next(it2)           # warm pool / first-spawn cost (NOT counted)
        t0 = time.perf_counter()
        seen = 0
        for b in it2:
            seen += b[0].shape[0]
        loader_ips[mode] = round(seen / (time.perf_counter() - t0), 2)
        if hasattr(dl, "_shutdown_pool"):
            dl._shutdown_pool()
    print(json.dumps({
        "metric": "image_record_iter_images_per_sec",
        "value": iter_ips, "unit": "images/sec",
        "vs_baseline": round(iter_ips / 3000.0, 4),  # ref decode target
        "threads": os.cpu_count() or 8, "batch": batch,
        "dataloader_images_per_sec": loader_ips,
        "workers": n_workers,
        # on a 1-CPU host the process pool CANNOT win (no parallel
        # hardware); judge the threads-vs-processes delta only when
        # host_cpus > workers
        "host_cpus": os.cpu_count() or 1,
    }))


def _ingest_decode_transform(img, label):
    """Decode-bound worker transform: resize + mirror + normalize, pure
    numpy/PIL (top level: must pickle into spawned workers)."""
    import numpy as np
    from PIL import Image
    a = np.asarray(img)
    im = Image.fromarray(a).resize((224, 224))
    out = np.asarray(im, np.float32)[:, ::-1].transpose(2, 0, 1) / 255.0
    return out, np.float32(label)


def _run_child(platform):
    """Run the benchmark pinned to `platform`; return (rc, stdout)."""
    env = dict(os.environ, MX_BENCH_CHILD="1", MX_BENCH_PLATFORM=platform)
    env.pop("MX_FORCE_CPU", None)
    env.pop("JAX_PLATFORMS", None)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"  # belt; run_bench's config.update is braces
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, timeout=CHILD_TIMEOUT_S,
                           stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except subprocess.TimeoutExpired as e:
        if e.stderr:  # the wedge's last words are the only diagnostics
            sys.stderr.write(e.stderr.decode(errors="replace")[-4000:])
        return 124, ""
    sys.stderr.write(r.stderr.decode(errors="replace")[-4000:])
    return r.returncode, r.stdout.decode(errors="replace")


def _captured_tpu_result(mode="resnet"):
    """Result persisted by tools/tpu_capture.py during a healthy tunnel
    window earlier in the round, or None.  Lets the driver's end-of-round
    bench report a real TPU number even if the tunnel is wedged right now."""
    if os.environ.get("MX_NO_CAPTURE_FALLBACK") == "1":
        return None  # capture loop's own bench child: never replay
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_CAPTURE.json")
    try:
        with open(path) as f:
            payload = json.load(f)
        # Staleness bound in the READER: the writer deletes last round's file
        # at loop start, but if the loop never ran this round we must not
        # replay a previous round's number.  Rounds are ~12h; 14h margin.
        import datetime
        age_s = (datetime.datetime.now(datetime.timezone.utc)
                 - datetime.datetime.strptime(
                     payload["captured_at"], "%Y-%m-%dT%H:%M:%S%z")
                 ).total_seconds()
        if age_s > 14 * 3600 or age_s < -300:
            return None
        # Round identity: the driver writes BENCH_r{N}.json at each round's
        # end.  A BENCH file that did not exist at capture time means a round
        # boundary passed since the capture — never replay across rounds
        # (a fixed age bound alone cannot guarantee that).
        import glob
        here = os.path.dirname(os.path.abspath(__file__))
        now_files = {os.path.basename(p)
                     for p in glob.glob(os.path.join(here, "BENCH_r*.json"))}
        if now_files - set(payload["bench_files_at_capture"]):
            return None
        key = {"bert": "bert_bench", "resnet": "resnet50_bench",
               "score": "score_bench"}.get(mode)
        if key is None:
            return None
        bench = payload["results"][key]
        if isinstance(bench, dict) and bench.get("device") not in (None, "cpu"):
            bench["captured_at"] = payload.get("captured_at")
            bench["replayed"] = True  # NOT a live end-of-round measurement
            # A consumer that parses only metric/value must not mistake a
            # replayed capture for a live run: the metric name itself says so.
            if not str(bench.get("metric", "")).endswith("_replayed"):
                bench["metric"] = str(bench.get("metric", "")) + "_replayed"
            return bench
    except (OSError, KeyError, ValueError, TypeError, AttributeError):
        pass
    return None


def main():
    if "--real-data" in sys.argv:
        run_real_data_bench()
        return
    if "--exchange" in sys.argv:
        run_exchange_bench()
        return
    if "--serve" in sys.argv:
        # CPU-friendly like --exchange: the serving engine's value on a
        # bench box is the batching/latency behavior, not model FLOPs
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("MX_FORCE_CPU", "1")
        if "--decode" in sys.argv:
            # ISSUE 15: continuous-vs-request-level decode comparison
            run_decode_bench()
            return
        run_serve_bench(routed="--routed" in sys.argv)
        return
    if "--warm-spawn" in sys.argv:
        # CPU-friendly: the lane measures spawn→first-PREDICT time of
        # subprocess replicas, which pin themselves to cpu
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("MX_FORCE_CPU", "1")
        run_warm_spawn_bench()
        return
    if os.environ.get("MX_BENCH_CHILD"):
        mode_env = os.environ.get("MX_BENCH_MODE")
        if mode_env == "bert":
            run_bert_bench()
        elif mode_env == "score":
            run_score_bench()
        elif mode_env == "eager":
            run_eager_bench()
        else:
            run_bench()
        return
    mode = "bert" if "--bert" in sys.argv else \
        ("score" if "--score" in sys.argv else
         ("eager" if "--eager" in sys.argv else "resnet"))
    if "--scan" in sys.argv:
        # diagnostic: run the measured iterations inside ONE jit (lax scan
        # over the step) — the delta vs the default per-step dispatch loop
        # is the per-step host/tunnel overhead
        os.environ["MX_BENCH_SCAN"] = "1"
    if "--mesh" in sys.argv:
        # ISSUE 14: --mesh data,fsdp[=N][,tp=N] arms the sharded lane in
        # the eager child (env so the probe/fallback respawn keeps it);
        # a CPU box fakes the mesh devices, set BEFORE any jax init
        at = sys.argv.index("--mesh")
        if at + 1 >= len(sys.argv):
            sys.stderr.write("bench.py: --mesh expects an axes argument "
                             "(e.g. --mesh data,fsdp=2)\n")
            sys.exit(2)
        mesh_arg = sys.argv[at + 1]
        os.environ["MX_BENCH_MESH"] = mesh_arg
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = \
                (os.environ.get("XLA_FLAGS", "") +
                 " --xla_force_host_platform_device_count=8").strip()
    if mode != "resnet":
        # same probe/fallback machinery, mode-specific child
        os.environ["MX_BENCH_MODE"] = mode
    from mxnet_tpu.base import cpu_pinned_by_user, probe_accelerator
    if cpu_pinned_by_user():
        candidates = ["cpu"]  # honor MX_FORCE_CPU=1 / JAX_PLATFORMS=cpu
    else:
        # MX_ASSUME_LIVE=1: the caller (tools/tpu_capture.py) probed the
        # tunnel immediately before spawning us — don't burn up to 150s of
        # the child budget re-proving it
        healthy = os.environ.get("MX_ASSUME_LIVE") == "1" \
            or probe_accelerator(PROBE_TIMEOUT_S)
        if not healthy:
            captured = _captured_tpu_result(mode)
            if captured is not None:
                # Tunnel is wedged now but was healthy earlier in the round:
                # report the captured real-TPU number over a CPU fallback.
                print(json.dumps(captured))
                return
        candidates = (["accelerator"] if healthy else []) + ["cpu"]
    for platform in candidates:
        rc, out = _run_child(platform)
        lines = [l for l in out.splitlines() if l.startswith("{")]
        if rc == 0 and lines:
            print(lines[-1])
            return
        sys.stderr.write("bench child on %r failed rc=%s\n" % (platform, rc))
        if platform == "accelerator":
            # Probe passed but the tunnel wedged MID-BENCH: a capture from
            # earlier in the round still beats the CPU fallback.
            captured = _captured_tpu_result(mode)
            if captured is not None:
                print(json.dumps(captured))
                return
    # Absolute last resort: a well-formed JSON error record, not a traceback.
    print(json.dumps({
        "metric": {"bert": "bert_base_pretrain_tokens_per_sec_per_chip",
                   "score": "model_zoo_inference_images_per_sec"}.get(
                       mode, "resnet50_train_images_per_sec_per_chip"),
        "value": 0.0,
        "unit": "tokens/sec" if mode == "bert" else "images/sec",
        "vs_baseline": 0.0,
        "error": "no backend could run the benchmark",
    }))
    sys.exit(0)


if __name__ == "__main__":
    main()
