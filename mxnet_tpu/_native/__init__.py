"""Native (C++) runtime components, built on demand.

The reference ships its runtime core (recordio, iterators, allocator) as
C++ in libmxnet.so; here the native pieces live in ``src/*.cc`` at the repo
root and are compiled lazily into this package directory with the system
toolchain (g++ — no pybind11 in this image, so the ABI is plain ``extern
"C"`` consumed via ctypes).

``load(name)`` returns the ctypes CDLL for ``src/<name>.cc``, compiling it
if the cached .so is missing or older than the source.  Raises OSError if
no compiler is available — callers fall back to their pure-Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(os.path.dirname(_DIR)), "src")
_lock = threading.Lock()
_cache = {}


# per-component link flags (the reference links OpenCV etc. into
# libmxnet.so; here each native piece declares its own system libs)
_LINK_FLAGS = {
    "imdecode": ["-ljpeg"],
}


def _build(name: str) -> str:
    src = os.path.join(_SRC, name + ".cc")
    out = os.path.join(_DIR, "lib%s.so" % name)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", src, "-o", out]
    cmd += _LINK_FLAGS.get(name, [])
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise OSError("native build failed for %s:\n%s" % (name, proc.stderr))
    return out


def load(name: str) -> ctypes.CDLL:
    with _lock:
        if name not in _cache:
            _cache[name] = ctypes.CDLL(_build(name))
        return _cache[name]
