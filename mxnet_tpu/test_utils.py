"""Test infrastructure (ported first, per SURVEY.md §7.1 M1: it IS the test
strategy).

Reference: python/mxnet/test_utils.py — check_numeric_gradient,
assert_almost_equal, check_consistency, same, rand_ndarray, default_context,
environment().
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .base import environment  # re-export (reference keeps it here)
from .device import Context, cpu, current_context
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "same", "almost_equal", "rand_ndarray", "rand_shape_nd",
           "check_numeric_gradient", "check_consistency", "environment",
           "default_rtol_atol"]

_default_ctx: List[Context] = []


def default_context() -> Context:
    return _default_ctx[-1] if _default_ctx else current_context()


def set_default_context(ctx: Context) -> None:
    _default_ctx.clear()
    _default_ctx.append(ctx)


_DTYPE_TOL = {
    np.dtype(np.float64): (1e-5, 1e-7),
    np.dtype(np.float32): (1e-4, 1e-5),
    np.dtype(np.float16): (1e-2, 1e-3),
}


def default_rtol_atol(dtype) -> tuple:
    return _DTYPE_TOL.get(np.dtype(dtype) if dtype != "bfloat16" else None,
                          (1e-2, 1e-2))


def _to_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return np.asarray(a)


def same(a, b) -> bool:
    return np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None) -> bool:
    a, b = _to_np(a), _to_np(b)
    rtol = rtol if rtol is not None else 1e-5
    atol = atol if atol is not None else 1e-20
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")) -> None:
    an, bn = _to_np(a), _to_np(b)
    if an.dtype == object or bn.dtype == object:
        raise AssertionError("non-numeric comparison")
    dt = an.dtype if an.dtype.kind == "f" else np.dtype(np.float32)
    drtol, datol = _DTYPE_TOL.get(dt, (1e-4, 1e-5))
    rtol = rtol if rtol is not None else drtol
    atol = atol if atol is not None else datol
    if not np.allclose(an.astype(np.float64), bn.astype(np.float64),
                       rtol=rtol, atol=atol, equal_nan=True):
        err = np.abs(an.astype(np.float64) - bn.astype(np.float64))
        rel = err / (np.abs(bn.astype(np.float64)) + atol)
        raise AssertionError(
            "%s and %s differ: max abs err %g, max rel err %g (rtol=%g atol=%g)"
            % (names[0], names[1], err.max() if err.size else 0,
               rel.max() if rel.size else 0, rtol, atol))


def rand_shape_nd(ndim: int, dim: int = 10) -> tuple:
    return tuple(np.random.randint(1, dim + 1, size=ndim).tolist())


def rand_ndarray(shape, stype: str = "default", density=None, dtype=None,
                 ctx: Optional[Context] = None) -> NDArray:
    if stype != "default":
        raise NotImplementedError("sparse rand_ndarray comes with sparse.py")
    arr = np.random.uniform(-1.0, 1.0, size=shape)
    return nd.array(arr, ctx=ctx or default_context(),
                    dtype=dtype or "float32")


# ---------------------------------------------------------------------------
# numeric gradient checking (reference: check_numeric_gradient) — central
# finite differences on the host against autograd's gradients.
# ---------------------------------------------------------------------------


def check_numeric_gradient(fn: Callable, inputs: Sequence[NDArray],
                           eps: float = 1e-3, rtol: float = 1e-2,
                           atol: float = 1e-3,
                           grad_nodes: Optional[Sequence[int]] = None) -> None:
    """fn: callable over NDArrays returning a single NDArray (any shape).
    Compares autograd grads of sum(fn(*inputs)) with central differences.
    Inputs should be float64-friendly magnitudes."""
    inputs = [x if isinstance(x, NDArray) else nd.array(x) for x in inputs]
    which = list(grad_nodes) if grad_nodes is not None else list(range(len(inputs)))
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        y = fn(*inputs)
        out = y.sum() if y.size > 1 else y
    out.backward()
    analytic = [inputs[i].grad.asnumpy().astype(np.float64) for i in which]

    host = [x.asnumpy().astype(np.float64) for x in inputs]

    def f_host(args):
        ndargs = [nd.array(a, dtype="float32") for a in args]
        r = fn(*ndargs)
        return float(r.sum().asscalar() if r.size > 1 else r.asscalar())

    for k, i in enumerate(which):
        numeric = np.zeros_like(host[i])
        flat = host[i].reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = f_host(host)
            flat[j] = orig - eps
            fm = f_host(host)
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        assert_almost_equal(analytic[k], numeric, rtol=rtol, atol=atol,
                            names=("autograd_grad[%d]" % i, "numeric_grad[%d]" % i))


def check_consistency(fn: Callable, inputs_np: Sequence[np.ndarray],
                      ctx_list: Sequence[Context], dtypes=("float32",),
                      rtol=None, atol=None) -> None:
    """Run fn on the same inputs across contexts/dtypes; assert agreement.

    Reference: check_consistency builds one symbol across [cpu, gpu]; here
    cross-ctx = cpu vs tpu (SURVEY.md §4.2 — the rebuild's most important
    test pattern)."""
    for dtype in dtypes:
        results = []
        for ctx in ctx_list:
            args = [nd.array(a, ctx=ctx, dtype=dtype) for a in inputs_np]
            out = fn(*args)
            outs = out if isinstance(out, (list, tuple)) else [out]
            results.append([o.asnumpy() for o in outs])
        base = results[0]
        for other, ctx in zip(results[1:], ctx_list[1:]):
            for a, b in zip(base, other):
                assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                    names=("ctx[%s]" % ctx_list[0], "ctx[%s]" % ctx))

def rand_shape_2d(dim0=10, dim1=10):
    """Random 2-D shape up to the given bounds (reference:
    test_utils.rand_shape_2d)."""
    return (np.random.randint(1, dim0 + 1),
            np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1),
            np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))
