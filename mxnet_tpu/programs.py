"""XLA program census: per-program compile-cost/memory accounting, a
retrace explainer, and a device-buffer census (ISSUE 10 tentpole).

The runtime could already trace *time* (telemetry spans) and *wire
bytes*, but its ~10 scattered ``jax.jit`` sites compiled blind: nothing
recorded compile latency, XLA cost, or device-memory footprint, and the
ROADMAP's FSDP acceptance ("per-chip memory dropping ~linearly with the
fsdp axis") was unmeasurable.  The Julia→TPU whole-program work (arxiv
1810.09868) and TF's cost-model surfaces (arxiv 1605.08695) both treat
compile cost and program footprint as first-class pipeline outputs —
this module is that layer:

* **Program registry** — every jit-creation site routes through
  :func:`register_program`, which returns a :class:`Program` wrapper.
  ``mode='aot'`` owns its executable cache explicitly
  (``jit(fn).lower(args).compile()``), so compile wall-time is bracketed
  exactly and the compiled object's ``memory_analysis()`` (argument /
  output / temp / generated-code bytes) and ``cost_analysis()`` (flops,
  bytes accessed) are captured where the backend provides them —
  explicitly ``None`` where it does not.  ``mode='light'`` keeps
  ``jax.jit``'s C++ dispatch for ultra-hot sites (eager per-op kernels,
  hybridize cache) and detects (re)traces with a zero-cost trace probe;
  compile time is the bracketed dispatch that traced.  Per-program
  numbers feed the telemetry registry —
  ``program_compile_seconds{program}``, ``program_temp_bytes{program}``,
  ``program_flops{program}``, ``program_retraces{program}`` — and ride
  the Prometheus/JSON exposition.

* **Retrace explainer** — each program record keeps the last trace
  signature (input avals + tree structure); on a retrace the structured
  diff (which arg's shape/dtype/weak-type changed, or that the tree
  structure itself did) is logged and recorded, so the serving
  zero-retrace gate and CompiledStep invalidations are diagnosable
  instead of just countable.

* **Device-buffer census** — :func:`buffer_census` buckets
  ``jax.live_arrays()`` by owner (params / optimizer_state /
  ef_residuals / serve / other; owners self-register via
  :func:`track_buffers`), and :class:`LeakDetector` turns step-over-step
  monotonic growth beyond ``MX_LEAK_WARN_BYTES`` into a gauge + warning,
  wired into the flight recorder (periodic step observer) and crash
  dumps (telemetry crash sections).

* **Program contracts** (ISSUE 11) — the registry's declarative face:
  :func:`declare_contract` lets each jit site state its abstract input
  signatures (``jax.ShapeDtypeStruct`` trees), expected donation set,
  temp-HBM budget and optionally a trace-closure spec.  Builders are
  lazy (declaring costs a dict insert); ``python -m tools.mxlint
  --contracts`` (tools/mxlint/contracts.py) lowers every declared case
  device-free and proves donation aliasing, the HBM budget and closure
  — see docs/TESTING.md §5.

Hot-path contract (mxlint-rooted): :meth:`Program.__call__`,
:func:`signature_of` and :meth:`ProgramRecord.note_compile` are
dispatch-time bookkeeping only — they read shapes/avals and never sync a
device; the census walk itself reads ``nbytes`` off live array handles
(host metadata, no transfer) and runs only periodically / at crash time.
"""
from __future__ import annotations

import functools
import logging
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from .base import get_env
from . import telemetry as _telemetry

__all__ = [
    "register_program", "Program", "ProgramRecord", "census_enabled",
    "program_table", "program_summary", "program_memory_bytes",
    "find_record", "reset_records",
    "signature_of", "diff_signatures",
    "track_buffers", "buffer_census", "leak_detector", "LeakDetector",
    "CENSUS_OWNERS",
    "CONTRACT_SCHEMA", "ContractCase", "ContractClosure",
    "ProgramContract", "declare_contract", "contracts",
    "contract_manifest", "reset_contracts",
]

logger = logging.getLogger("mxnet_tpu.programs")


def census_enabled() -> bool:
    """MX_PROGRAM_CENSUS (default on): program registry + buffer census."""
    return bool(get_env("MX_PROGRAM_CENSUS", dtype=bool))


# ---------------------------------------------------------------------------
# Trace signatures + the retrace explainer
# ---------------------------------------------------------------------------

def _leaf_sig(x):
    """One leaf's trace identity.  jax arrays/tracers contribute their
    aval (shape, dtype, weak_type) plus their sharding — exactly
    jax.jit's cache key; an AOT executable strictly rejects inputs on a
    different device, so the device must key the cache too.
    ndarray-likes contribute a (shape, dtype) tuple; python scalars
    their VALUE (conservative: correct under static_argnums, and no
    routed site passes scalars as traced operands)."""
    aval = getattr(x, "aval", None)
    if aval is not None:
        return ("aval", aval, getattr(x, "sharding", None))
    shape = getattr(x, "shape", None)
    if shape is not None and hasattr(x, "dtype"):
        return ("arr", tuple(int(s) for s in shape), str(x.dtype))
    if isinstance(x, (bool, int, float, complex, str, bytes)):
        return ("py", type(x).__name__, x)
    return ("obj", type(x).__name__)


def signature_of(args: tuple, kwargs: Optional[dict] = None) -> Tuple:
    """(treedef, per-leaf identity) of a call — the program cache key.
    Reads shapes/avals only; never touches device data."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    return (treedef, tuple(_leaf_sig(x) for x in leaves))


class _SigLeaf:
    """Opaque wrapper so a stored leaf signature (which may itself be a
    tuple) survives tree_unflatten as ONE leaf when the explainer
    rebuilds arg paths."""

    __slots__ = ("sig",)

    def __init__(self, sig):
        self.sig = sig


def _leaf_desc(sig) -> Dict[str, Any]:
    """Human/JSON form of one leaf signature."""
    if isinstance(sig, tuple) and sig and sig[0] == "aval":
        _, aval, sharding = sig
        out = {"shape": tuple(int(s) for s in aval.shape),
               "dtype": str(aval.dtype),
               "weak_type": bool(getattr(aval, "weak_type", False))}
        if sharding is not None:
            out["device"] = str(sharding)
        return out
    if isinstance(sig, tuple) and sig and sig[0] == "arr":
        return {"shape": sig[1], "dtype": sig[2], "weak_type": False}
    if isinstance(sig, tuple) and sig and sig[0] == "py":
        return {"py": sig[1], "value": sig[2]}
    return {"opaque": str(sig)}


def _paths_for(treedef, sigs) -> List[str]:
    tree = jax.tree_util.tree_unflatten(treedef,
                                        [_SigLeaf(s) for s in sigs])
    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, _SigLeaf))
    return [jax.tree_util.keystr(path) for path, _ in flat]


def diff_signatures(old: Tuple, new: Tuple) -> Optional[Dict[str, Any]]:
    """Structured explanation of why `new` could not reuse `old`'s
    executable: either the argument tree structure changed, or specific
    leaves changed shape/dtype/weak-type.  None when identical."""
    if old == new:
        return None
    old_td, old_sigs = old
    new_td, new_sigs = new
    if old_td != new_td:
        return {"kind": "tree_structure",
                "before": str(old_td), "after": str(new_td)}
    paths = _paths_for(new_td, new_sigs)
    changed = []
    for path, a, b in zip(paths, old_sigs, new_sigs):
        if a == b:
            continue
        da, db = _leaf_desc(a), _leaf_desc(b)
        if da.get("dtype") != db.get("dtype") and \
                da.get("shape") == db.get("shape"):
            change = "dtype"
        elif da.get("shape") != db.get("shape") and \
                da.get("dtype") == db.get("dtype"):
            change = "shape"
        elif da.get("shape") == db.get("shape") and \
                da.get("dtype") == db.get("dtype") and \
                da.get("device") != db.get("device"):
            # same logical value, different placement: a LAYOUT change.
            # When the device set is unchanged but the partitioning is
            # (same mesh, new PartitionSpec — the FSDP resharding path),
            # call it what it is: a sharding change, not a device move.
            change = "device"
            try:
                sa = a[2] if isinstance(a, tuple) and a[0] == "aval" else None
                sb = b[2] if isinstance(b, tuple) and b[0] == "aval" else None
                if sa is not None and sb is not None and \
                        getattr(sa, "device_set", None) == \
                        getattr(sb, "device_set", None):
                    change = "sharding"
            except Exception:
                pass
        else:
            change = "leaf"
        changed.append({"arg": path, "change": change,
                        "before": da, "after": db})
    return {"kind": "leaves", "changed": changed}


def _format_diff(diff: Dict[str, Any]) -> str:
    if diff["kind"] == "tree_structure":
        return "argument tree structure changed: %s -> %s" % (
            diff["before"], diff["after"])
    parts = []
    for c in diff["changed"][:8]:
        parts.append("%s %s: %s -> %s" % (
            c["arg"], c["change"], c["before"], c["after"]))
    more = len(diff["changed"]) - 8
    if more > 0:
        parts.append("(+%d more)" % more)
    return "; ".join(parts)


# ---------------------------------------------------------------------------
# Program records
# ---------------------------------------------------------------------------

def _memory_dict(compiled) -> Optional[Dict[str, Any]]:
    """CompiledMemoryStats → plain dict, or None where the backend does
    not provide memory_analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    try:
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except AttributeError:
        return None


def _cost_dict(compiled) -> Optional[Dict[str, Any]]:
    """cost_analysis() → {flops, bytes_accessed}, or None."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, Any] = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


class ProgramRecord:
    """Aggregated accounting for one named program (all its wrapper
    instances and executables).

    ``specializing`` records (ISSUE 13): some sites aggregate MANY
    expected shape/rank specializations under one name — per-op eager
    kernels, the hybridize cache, the fused optimizer's per-model tree
    kernels.  For those, a FRESH signature is an expected
    specialization (counted separately), and ``retraces`` counts only
    a rebuild of an ALREADY-SEEN signature — genuine cache thrash.
    Strict records (the default: step/serve/mesh programs) keep the
    original semantics: any signature change is a retrace."""

    def __init__(self, name: str, mode: str, specializing: bool = False):
        self.name = name
        self.mode = mode
        self.specializing = bool(specializing)
        self._lock = threading.Lock()
        self.compiles = 0                 # executables built
        self.retraces = 0                 # compiles whose signature
        #                                   differed from the last seen
        #                                   (specializing: re-compiles
        #                                   of a KNOWN signature)
        self.specializations = 0          # fresh-signature compiles of
        #                                   a specializing program
        self.cache_hits = 0               # executables deserialized from
        #                                   the persistent compile cache
        self.deserialize_seconds_total = 0.0
        self._seen_sigs: set = set()
        self.compile_seconds_total = 0.0
        self.compile_seconds_max = 0.0
        self.last_compile_seconds: Optional[float] = None
        self.memory: Optional[Dict[str, Any]] = None    # latest compile's
        self.cost: Optional[Dict[str, Any]] = None
        self.temp_bytes_peak: Optional[int] = None
        self.last_sig: Optional[Tuple] = None
        self.last_retrace: Optional[Dict[str, Any]] = None
        labels = {"program": name}
        reg = _telemetry.registry
        self._h_compile = reg.histogram(
            "program_compile_seconds",
            doc="wall-clock trace+lower+compile time per XLA program",
            labels=labels)
        self._c_retrace = reg.counter(
            "program_retraces",
            doc="program rebuilds whose input signature changed vs the "
                "previous trace (see the retrace explainer log)",
            labels=labels)
        self._g_temp = reg.gauge(
            "program_temp_bytes",
            doc="XLA memory_analysis temp allocation of the latest "
                "executable", labels=labels)
        self._g_flops = reg.gauge(
            "program_flops",
            doc="XLA cost_analysis flops of the latest executable",
            labels=labels)

    def _absorb_metadata_locked(self, mem, cost) -> None:
        """Fold one executable's memory/cost analysis into the record
        (caller holds self._lock) — shared by compiled and
        cache-deserialized builds so their census columns can never
        diverge."""
        if mem is not None:
            self.memory = mem
            tb = mem["temp_bytes"]
            if self.temp_bytes_peak is None or tb > self.temp_bytes_peak:
                self.temp_bytes_peak = tb
        if cost is not None:
            self.cost = cost

    def _publish_metadata_gauges(self, mem, cost) -> None:
        if mem is not None:
            self._g_temp.set(mem["temp_bytes"])
        if cost is not None and "flops" in cost:
            self._g_flops.set(cost["flops"])

    def note_compile(self, seconds: float, sig: Tuple,
                     compiled=None) -> None:
        """Record one executable build: timing, optional AOT metadata,
        and the retrace explainer's signature diff."""
        mem = _memory_dict(compiled) if compiled is not None else None
        cost = _cost_dict(compiled) if compiled is not None else None
        diff = None
        is_retrace = False
        with self._lock:
            self.compiles += 1
            self.compile_seconds_total += seconds
            if seconds > self.compile_seconds_max:
                self.compile_seconds_max = seconds
            self.last_compile_seconds = seconds
            if self.last_sig is not None:
                diff = diff_signatures(self.last_sig, sig)
                if diff is not None:
                    if self.specializing and sig not in self._seen_sigs:
                        # fresh shape at a specializing site: expected
                        # (per-op rank/shape specialization is the
                        # light-census contract), counted separately
                        self.specializations += 1
                    else:
                        is_retrace = True
                        self.retraces += 1
                        self.last_retrace = {"diff": diff,
                                             "compile_seconds": seconds}
            self._seen_sigs.add(sig)
            self.last_sig = sig
            self._absorb_metadata_locked(mem, cost)
        self._h_compile.observe(seconds)
        self._publish_metadata_gauges(mem, cost)
        if is_retrace:
            self._c_retrace.inc()
            logger.info("program %r retraced (compile %.3fs): %s",
                        self.name, seconds, _format_diff(diff))
        elif diff is not None:
            logger.debug("program %r specialized (compile %.3fs): %s",
                         self.name, seconds, _format_diff(diff))

    def note_cache_hit(self, seconds: float, sig: Tuple,
                       compiled=None) -> None:
        """Record one executable DESERIALIZED from the persistent
        compile cache: no compile happened, no retrace is charged —
        ``compile_seconds_total`` stays the cost actually paid (the
        warm-restart acceptance number), deserialize time accumulates
        separately.  The signature still lands in the seen-set so a
        later genuine rebuild of it is attributed correctly."""
        mem = _memory_dict(compiled) if compiled is not None else None
        cost = _cost_dict(compiled) if compiled is not None else None
        with self._lock:
            self.cache_hits += 1
            self.deserialize_seconds_total += seconds
            self._seen_sigs.add(sig)
            self.last_sig = sig
            self._absorb_metadata_locked(mem, cost)
        self._publish_metadata_gauges(mem, cost)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "name": self.name,
                "mode": self.mode,
                "specializing": self.specializing,
                "compiles": self.compiles,
                "retraces": self.retraces,
                "specializations": self.specializations,
                "cache_hits": self.cache_hits,
                "deserialize_seconds": round(
                    self.deserialize_seconds_total, 6),
                "compile_seconds": {
                    "total": round(self.compile_seconds_total, 6),
                    "max": round(self.compile_seconds_max, 6),
                    "last": None if self.last_compile_seconds is None
                    else round(self.last_compile_seconds, 6),
                },
                "memory": dict(self.memory) if self.memory else None,
                "cost": dict(self.cost) if self.cost else None,
                "temp_bytes_peak": self.temp_bytes_peak,
                "last_retrace": self.last_retrace,
            }


_records_lock = threading.Lock()
_records: Dict[str, ProgramRecord] = {}


def _record(name: str, mode: str,
            specializing: bool = False) -> ProgramRecord:
    with _records_lock:
        rec = _records.get(name)
        if rec is None:
            rec = ProgramRecord(name, mode, specializing=specializing)
            _records[name] = rec
    return rec


def find_record(name: str) -> Optional[ProgramRecord]:
    with _records_lock:
        return _records.get(name)


def program_table() -> Dict[str, Dict[str, Any]]:
    """{program name: record snapshot} — what bench.py embeds and crash
    dumps carry."""
    with _records_lock:
        recs = list(_records.values())
    return {rec.name: rec.snapshot() for rec in recs}


def program_summary() -> Dict[str, Any]:
    """Roll-up across every registered program: total compile seconds,
    total retraces, peak temp bytes — the numbers the bench sentinel
    gates on."""
    table = program_table()
    total_s = sum(t["compile_seconds"]["total"] for t in table.values())
    peak_temp = [t["temp_bytes_peak"] for t in table.values()
                 if t["temp_bytes_peak"] is not None]
    return {
        "programs": len(table),
        "compiles": sum(t["compiles"] for t in table.values()),
        "retraces": sum(t["retraces"] for t in table.values()),
        "specializations": sum(t["specializations"]
                               for t in table.values()),
        "cache_hits": sum(t["cache_hits"] for t in table.values()),
        "deserialize_seconds_total": round(
            sum(t["deserialize_seconds"] for t in table.values()), 6),
        "compile_seconds_total": round(total_s, 6),
        "peak_temp_bytes": max(peak_temp) if peak_temp else None,
    }


def program_memory_bytes(prefix: str) -> Dict[str, int]:
    """Aggregate ``memory_analysis`` bytes over every registered
    program whose name starts with ``prefix`` (ISSUE 20): the HBM
    bin-packer's per-model program-side footprint.  ``temp_bytes_peak``
    is the max transient allocation any one of the model's programs
    needs live at dispatch (programs run one at a time per replica);
    argument/output bytes are informational — the live arrays they
    alias are already counted by :func:`buffer_census`."""
    table = program_table()
    out = {"programs": 0, "temp_bytes_peak": 0,
           "argument_bytes_max": 0, "output_bytes_max": 0}
    for name, t in table.items():
        if not name.startswith(prefix):
            continue
        out["programs"] += 1
        tb = t.get("temp_bytes_peak")
        if tb:
            out["temp_bytes_peak"] = max(out["temp_bytes_peak"],
                                         int(tb))
        mem = t.get("memory") or {}
        for src, dst in (("argument_bytes", "argument_bytes_max"),
                         ("output_bytes", "output_bytes_max")):
            b = mem.get(src)
            if b:
                out[dst] = max(out[dst], int(b))
    return out


def program_count() -> int:
    with _records_lock:
        return len(_records)


def reset_records() -> None:
    """Drop every record (tests).  Telemetry instruments persist —
    readers should use fresh names or deltas."""
    with _records_lock:
        _records.clear()


# ---------------------------------------------------------------------------
# The wrapper
# ---------------------------------------------------------------------------

class Program:
    """Census-wrapped jitted callable.

    ``aot=True``: per-signature executable cache via
    ``jit.lower(...).compile()`` — exact compile bracketing + XLA
    memory/cost metadata.  Falls back permanently to plain jit dispatch
    if the site cannot lower ahead-of-time (exotic shardings etc.).

    ``aot=False`` (light): ``jax.jit`` keeps its C++ dispatch; a trace
    probe inside the traced fn bumps a counter, so a dispatch that
    traced is detected after the fact and its wall time recorded as the
    compile cost (memory/cost stay explicitly None).
    """

    def __init__(self, name: str, mode: str, fn: Callable,
                 jit_kw: Dict[str, Any], aot: bool,
                 specializing: bool = False):
        self._name = name
        self._mode = mode
        self._specializing = bool(specializing)
        self._fn = fn            # compile-cache function fingerprint
        self._record: Optional[ProgramRecord] = None
        self._seq = 0
        self._noted = 0     # compiles already recorded (under _cache_lock)

        def _trace_probe(*a, **k):
            # runs at TRACE time only (host side); the attribute write
            # is the point — it marks "this dispatch compiled"
            self._seq += 1
            return fn(*a, **k)

        functools.update_wrapper(_trace_probe, fn, updated=())
        self._jit = jax.jit(_trace_probe, **jit_kw)
        self._jit_kw = dict(jit_kw)
        self._aot = aot
        self._cache: Dict[Tuple, Any] = {}
        self._cache_lock = threading.Lock()
        # signatures whose executable came off the persistent compile
        # cache (under _cache_lock) — per-INSTANCE, so warm()-style
        # callers can tell a deserialized build from a cold compile
        # without racing on process-global counters
        self._from_cache_sigs: set = set()

    @property
    def jit_kw(self) -> Dict[str, Any]:
        """The jit kwargs this site registered with (donate_argnums,
        static_argnums, shardings) — what the contract verifier proves
        against."""
        return dict(self._jit_kw)

    def lower(self, *args, **kwargs):
        """AOT-lower the wrapped jit without dispatching — the contract
        verifier's device-free entry point (works with
        jax.ShapeDtypeStruct trees; no buffers are materialized)."""
        return self._jit.lower(*args, **kwargs)

    @property
    def record(self) -> ProgramRecord:
        """Get-or-create LAZILY at first compile: a registered-but-never-
        dispatched wrapper (e.g. a module-level kernel the workload never
        runs) must not pollute the table with a zero-compile row."""
        if self._record is None:
            self._record = _record(self._name, self._mode,
                                   specializing=self._specializing)
        return self._record

    @property
    def executables(self) -> int:
        with self._cache_lock:
            return len(self._cache)

    def _compile(self, sig, args, kwargs):
        # persistent compile cache (ISSUE 13): a warm restart
        # deserializes the executable this process's predecessor built —
        # no trace, no lower, no XLA compile.  Any miss (absent entry,
        # version/topology skew, corrupt payload) falls through to the
        # normal compile below, which then publishes the entry.
        from . import compile_cache as _cc
        ckey = None
        if _cc.enabled():
            ckey = _cc.cache_key(self._name, sig, fn=self._fn,
                                 jit_kw=self._jit_kw)
            t0 = time.perf_counter()
            cached = _cc.load(self._name, ckey)
            if cached is not None:
                dt = time.perf_counter() - t0
                with self._cache_lock:
                    kept = self._cache.setdefault(sig, cached)
                    self._from_cache_sigs.add(sig)
                    self._noted = self._seq
                if kept is cached:
                    self.record.note_cache_hit(dt, sig, compiled=kept)
                return kept
        t0 = time.perf_counter()
        try:
            compiled = self._jit.lower(*args, **kwargs).compile()
        except Exception as e:
            # this site cannot AOT-lower (e.g. layout/sharding the
            # lowering path rejects): census degrades to light mode.
            # The failed lower may still have TRACED (bumping the probe)
            # — consume those bumps so the light path only counts its
            # own subsequent trace, not phantom compiles.
            with self._cache_lock:
                self._noted = self._seq
            self._aot = False
            logger.info("programs: AOT census unavailable for %r (%s: "
                        "%s); using plain jit dispatch",
                        self._name, type(e).__name__, e)
            return None
        dt = time.perf_counter() - t0
        with self._cache_lock:
            kept = self._cache.setdefault(sig, compiled)
            self._noted = self._seq     # AOT owns these probe bumps
        if kept is compiled:
            # two racing cold-callers both compile; the one whose
            # executable the cache kept records the build — compiles
            # stays exact
            self.record.note_compile(dt, sig, compiled=kept)
            if ckey is not None:
                _cc.store(self._name, ckey, kept)
        return kept

    def ensure_compiled(self, *args, **kwargs):
        """Build (or warm-load from the persistent compile cache) the
        executable for this argument signature WITHOUT dispatching it.

        Returns a truthy provenance string when an AOT executable is
        ready — ``"hit"`` (deserialized from the persistent cache, this
        instance, this signature), ``"compiled"`` (built cold) or
        ``"ready"`` (already in the in-memory table) — and False in
        light mode or after an AOT fallback, where the caller must
        dispatch normally."""
        if not self._aot:
            return False
        sig = signature_of(args, kwargs)
        with self._cache_lock:
            if sig in self._cache:
                return "hit" if sig in self._from_cache_sigs else "ready"
        if self._compile(sig, args, kwargs) is None:
            return False
        with self._cache_lock:
            return "hit" if sig in self._from_cache_sigs else "compiled"

    def __call__(self, *args, **kwargs):
        if self._aot:
            sig = signature_of(args, kwargs)
            compiled = self._cache.get(sig)
            if compiled is None:
                compiled = self._compile(sig, args, kwargs)
            if compiled is not None:
                return compiled(*args, **kwargs)
        seq = self._seq
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        if self._seq != seq:
            dt = time.perf_counter() - t0
            # claim the trace under the lock: two threads dispatching
            # concurrently both observe the bump, but only the first
            # records it — no double-counted compiles / phantom retraces
            with self._cache_lock:
                claimed = self._seq - self._noted
                self._noted = self._seq
            for _ in range(claimed):
                self.record.note_compile(dt, signature_of(args, kwargs))
        return out


def register_program(name: str, fn: Callable, mode: str = "aot",
                     specializing: bool = False, **jit_kw) -> Callable:
    """Route one jit-creation site through the program census.

    Drop-in for ``jax.jit(fn, **jit_kw)``; returns a callable.  ``name``
    is the program's stable registry identity (wrappers sharing a name
    aggregate into one record — e.g. every hybridize cache entry of one
    block class).  ``mode='aot'`` for programs built once and dispatched
    per step/batch; ``mode='light'`` for per-op hot paths.
    ``specializing=True`` marks a site whose record expects many
    shape/rank specializations under one name (per-op kernels, the
    hybridize cache, fused optimizer tree kernels): fresh signatures
    count as ``specializations``, and ``retraces`` counts only genuine
    rebuilds of an already-seen signature.  With
    ``MX_PROGRAM_CENSUS=0`` this is exactly ``jax.jit``.
    """
    from . import compile_cache as _cc
    if _cc.enabled():
        _cc.activate()          # idempotent; arms the XLA-level layer
    if not census_enabled():
        return jax.jit(fn, **jit_kw)
    return Program(name, mode, fn, jit_kw, aot=(mode == "aot"),
                   specializing=specializing)


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the registry's declarative face
# ---------------------------------------------------------------------------

# bumped when the manifest JSON layout changes; tools/bench_compare.py
# --check-schema validates checked-in manifests against this version
CONTRACT_SCHEMA = 1


class ContractCase:
    """One concrete, device-free lowering of a contracted program.

    ``args``/``kwargs`` are abstract input trees (``jax.ShapeDtypeStruct``
    leaves — no buffers); ``target`` is the site's own registered wrapper
    (anything with ``.lower``, i.e. a :class:`Program` or a ``jax.jit``
    object) so the verifier proves the EXACT jit spec the runtime ships.
    Alternatively ``fn``+``jit_kw`` hand the verifier a raw traceable
    body to jit itself (the kvstore exchange bodies, which normally
    inline into the step program, are contracted standalone this way).
    """

    __slots__ = ("program", "label", "target", "fn", "jit_kw", "args",
                 "kwargs")

    def __init__(self, program: str, args: tuple, kwargs=None,
                 label: Optional[str] = None, target=None,
                 fn: Optional[Callable] = None, jit_kw=None):
        if (target is None) == (fn is None):
            raise ValueError("ContractCase needs exactly one of "
                             "target= (a lowerable) or fn= (a raw body)")
        self.program = str(program)
        self.label = str(label if label is not None else program)
        self.target = target
        self.fn = fn
        self.jit_kw = dict(jit_kw or {})
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})

    def lower(self):
        if self.target is not None:
            return self.target.lower(*self.args, **self.kwargs)
        return jax.jit(self.fn, **self.jit_kw).lower(*self.args,
                                                     **self.kwargs)


class ContractClosure:
    """Static zero-retrace proof spec: ``points`` enumerates the
    workload's reachable dispatch points (every admissible serve batch
    size, every configured scan window, ...) and ``resolve(point)``
    returns the abstract argument tree that point would dispatch with —
    or None when the runtime provably rejects the point before the jit
    (serve admission refusing an over-bucket batch).  The verifier
    asserts every resolved signature is one of the declared cases'
    signatures; a miss is an unproven shape, rendered through the
    retrace explainer's diff."""

    __slots__ = ("points", "resolve")

    def __init__(self, points, resolve: Callable):
        self.points = list(points)
        self.resolve = resolve


class ProgramContract:
    """Declared invariants of one program family.

    ``build()`` is LAZY — declaring a contract at import time costs a
    dict insert; only the verifier (``python -m tools.mxlint
    --contracts``) ever builds the cases.  ``donate_argnums`` is the
    EXPECTED donation set: the verifier proves each donated leaf
    actually appears in the lowered executable's input→output aliasing
    (a dropped donation doubles HBM on TPU while CPU runs clean).
    ``temp_budget_bytes`` caps the compiled ``memory_analysis`` temp
    allocation — the static HBM-creep gate."""

    __slots__ = ("name", "build", "donate_argnums", "temp_budget_bytes",
                 "closure", "description", "origin")

    def __init__(self, name: str, build: Callable,
                 donate_argnums: Tuple[int, ...] = (),
                 temp_budget_bytes: Optional[int] = None,
                 closure: Optional[ContractClosure] = None,
                 description: str = "",
                 origin: Optional[Tuple[str, int]] = None):
        self.name = str(name)
        self.build = build
        self.donate_argnums = tuple(sorted(int(i) for i in donate_argnums))
        self.temp_budget_bytes = None if temp_budget_bytes is None \
            else int(temp_budget_bytes)
        self.closure = closure
        self.description = str(description)
        # (file, line) of the declaring site — contract findings anchor
        # there, like any other mxlint diagnostic
        self.origin = origin

    def manifest_entry(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "donate_argnums": list(self.donate_argnums),
            "temp_budget_bytes": self.temp_budget_bytes,
            "closure_points": (
                None if self.closure is None
                else [str(p) for p in self.closure.points]
                if isinstance(self.closure, ContractClosure)
                # lazy closure: built (with its cases) only when the
                # verifier runs — the static manifest records that one
                # exists without paying the build
                else "deferred"),
            "description": self.description,
        }


_contracts_lock = threading.Lock()
_contracts: Dict[str, ProgramContract] = {}


def declare_contract(name: str, build: Callable, *,
                     donate_argnums: Tuple[int, ...] = (),
                     temp_budget_bytes: Optional[int] = None,
                     closure: Optional[ContractClosure] = None,
                     description: str = "") -> ProgramContract:
    """Declare the contract for one program family.  ``build`` returns
    the :class:`ContractCase` list when the verifier runs; everything
    else is metadata recorded now.  Redeclaring a name replaces the
    entry (module reloads in tests)."""
    import sys as _sys
    frame = _sys._getframe(1)
    origin = (frame.f_code.co_filename, frame.f_lineno)
    c = ProgramContract(name, build, donate_argnums=donate_argnums,
                        temp_budget_bytes=temp_budget_bytes,
                        closure=closure, description=description,
                        origin=origin)
    with _contracts_lock:
        _contracts[c.name] = c
    return c


def contracts() -> List[ProgramContract]:
    with _contracts_lock:
        return [_contracts[k] for k in sorted(_contracts)]


def contract_manifest() -> Dict[str, Any]:
    """The declared (not built) manifest — what ships in
    tools/mxlint/contracts.json and what bench_compare --check-schema
    validates."""
    return {"schema": CONTRACT_SCHEMA,
            "contracts": [c.manifest_entry() for c in contracts()]}


def reset_contracts() -> None:
    with _contracts_lock:
        _contracts.clear()


# ---------------------------------------------------------------------------
# Device-buffer census
# ---------------------------------------------------------------------------

# claim priority, most specific first: a Servable's version arrays are
# the same buffers its source block's Parameters hold — the serving
# owner wins so a deployed version's footprint is visible as such.
# kv_cache (ISSUE 15) holds the decode engine's device-resident KV
# pool + per-slot token/length state, donated across decode steps —
# the bucket whose bytes must stay FLAT across generations.
# kv_pages (ISSUE 18) is the PAGED decode engine's shared page heap +
# block-table state — same flatness contract as kv_cache, but the
# bucket is sized in pages, not slots, so admission headroom reads off
# it directly.
CENSUS_OWNERS = ("serve", "kv_cache", "kv_pages", "ef_residuals",
                 "optimizer_state", "params")

_owners_lock = threading.Lock()
# obj -> (kind, extractor(obj) -> iterable of arrays/NDArrays)
_owners: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def track_buffers(kind: str, obj, extract: Callable) -> None:
    """Register `obj` as a buffer owner for the census.  `extract(obj)`
    yields its current device arrays (jax arrays or NDArray-likes) when
    the census runs; held weakly, so owners never leak through the
    census itself."""
    try:
        with _owners_lock:
            _owners[obj] = (str(kind), extract)
    except TypeError:
        pass            # not weakref-able: stay uncounted ("other")


def _owned_ids() -> Dict[str, set]:
    with _owners_lock:
        items = list(_owners.items())
    by_kind: Dict[str, set] = {k: set() for k in CENSUS_OWNERS}
    for obj, (kind, extract) in items:
        ids = by_kind.setdefault(kind, set())
        try:
            arrays = extract(obj)
        except Exception:
            continue
        for a in arrays or ():
            a = getattr(a, "_jax", a)
            if a is not None:
                ids.add(id(a))
    return by_kind


def _per_chip_nbytes(a, nbytes: int) -> int:
    """Bytes ONE chip holds of array `a` (ISSUE 14): the shard extent
    under the array's sharding — full bytes when replicated or
    single-device, ``nbytes / prod(sharded axes)`` when sheet/tensor
    sharded.  This is the number that must drop ~linearly with the fsdp
    axis for params + optimizer state."""
    try:
        sh = getattr(a, "sharding", None)
        if sh is None:
            return nbytes
        shape = tuple(a.shape)
        shard_shape = sh.shard_shape(shape)
        full = 1
        part = 1
        for d in shape:
            full *= int(d)
        for d in shard_shape:
            part *= int(d)
        if full <= 0:
            return nbytes
        return (nbytes * part) // full
    except Exception:
        return nbytes


def buffer_census() -> Dict[str, Any]:
    """Bucket every live device array by owner.

    Walks ``jax.live_arrays()`` host-side (array handles + nbytes
    metadata — no device sync, no transfer) and attributes each to the
    first owner bucket claiming its id; unclaimed arrays land in
    ``other`` (activations in flight, test droppings, leaks).  Each
    bucket reports global ``bytes`` and sharding-aware
    ``bytes_per_chip`` (the per-device footprint: a mesh-sharded param's
    shard extent, the full value when replicated) — the acceptance
    series for the FSDP lane."""
    by_kind = _owned_ids()
    order = [k for k in CENSUS_OWNERS if k in by_kind] + \
        [k for k in by_kind if k not in CENSUS_OWNERS]
    out: Dict[str, Any] = {k: {"count": 0, "bytes": 0,
                               "bytes_per_chip": 0}
                           for k in order + ["other"]}
    total = 0
    total_chip = 0
    n = 0
    try:
        live = jax.live_arrays()
    except Exception:
        live = []
    for a in live:
        try:
            if getattr(a, "is_deleted", lambda: False)():
                continue
            nbytes = int(a.nbytes)
        except Exception:
            continue
        chip_bytes = _per_chip_nbytes(a, nbytes)
        aid = id(a)
        for kind in order:
            if aid in by_kind[kind]:
                slot = out[kind]
                break
        else:
            slot = out["other"]
        slot["count"] += 1
        slot["bytes"] += nbytes
        slot["bytes_per_chip"] += chip_bytes
        total += nbytes
        total_chip += chip_bytes
        n += 1
    out["total_bytes"] = total
    out["total_bytes_per_chip"] = total_chip
    out["n_arrays"] = n
    return out


class LeakDetector:
    """Step-over-step live-byte growth detector.

    Each :meth:`check` snapshots the census, publishes per-owner
    ``census_live_bytes{owner}`` gauges, and accumulates consecutive
    total growth; when the streak exceeds ``MX_LEAK_WARN_BYTES`` the
    ``census_leak_bytes`` gauge latches the streak size,
    ``census.leak_trips`` increments and a warning names the growing
    buckets.  Any shrink resets the streak (steady-state training
    reuses buffers; a true leak only ever grows)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._prev_total: Optional[int] = None
        self._prev_census: Optional[Dict[str, Any]] = None
        self._growth = 0
        self._tripped = False
        reg = _telemetry.registry
        self._g_leak = reg.gauge(
            "census_leak_bytes",
            doc="consecutive step-over-step live-byte growth "
                "(0 until it exceeds MX_LEAK_WARN_BYTES)")
        self._c_trips = reg.counter(
            "census.leak_trips",
            doc="times the buffer-census leak detector tripped")

    def reset(self) -> None:
        with self._lock:
            self._prev_total = None
            self._prev_census = None
            self._growth = 0
            self._tripped = False
        self._g_leak.set(0)

    def check(self) -> Dict[str, Any]:
        census = buffer_census()
        reg = _telemetry.registry
        for kind, slot in census.items():
            if isinstance(slot, dict):
                reg.gauge("census_live_bytes",
                          doc="live device bytes by owner bucket",
                          labels={"owner": kind}).set(slot["bytes"])
        try:
            warn_bytes = int(get_env("MX_LEAK_WARN_BYTES", 64 << 20, int)
                             or 0)
        except (TypeError, ValueError):
            warn_bytes = 64 << 20
        total = census["total_bytes"]
        growers = []
        with self._lock:
            if self._prev_total is not None:
                delta = total - self._prev_total
                if delta > 0:
                    self._growth += delta
                    prev = self._prev_census or {}
                    for kind, slot in census.items():
                        if not isinstance(slot, dict):
                            continue
                        before = (prev.get(kind) or {}).get("bytes", 0)
                        if slot["bytes"] > before:
                            growers.append(
                                (kind, slot["bytes"] - before))
                elif delta < 0:
                    # only a SHRINK resets the streak — a flat plateau
                    # between growth steps (allocator reuse) must not
                    # hide a monotonically growing leak
                    self._growth = 0
                    self._tripped = False
            self._prev_total = total
            self._prev_census = census
            growth = self._growth
            tripped = warn_bytes > 0 and growth >= warn_bytes
            first_trip = tripped and not self._tripped
            self._tripped = tripped
        self._g_leak.set(growth if tripped else 0)
        if first_trip:
            self._c_trips.inc()
            logger.warning(
                "buffer-census leak suspect: live bytes grew %d over "
                "consecutive checks (MX_LEAK_WARN_BYTES=%d); growing "
                "buckets this check: %s; census: %s",
                growth, warn_bytes,
                ", ".join("%s+%d" % g for g in growers) or "other",
                {k: v for k, v in census.items() if isinstance(v, dict)})
        return {"census": census, "growth_bytes": growth,
                "tripped": tripped}


leak_detector = LeakDetector()

# Flight-recorder wiring: every Nth step record carries the census
# totals + leak streak (cheap enough to ride along; a live_arrays walk
# per step would not be).
_CENSUS_EVERY = 16
_census_tick = [0]
_census_tick_lock = threading.Lock()


def _step_census_observer() -> Optional[Dict[str, Any]]:
    if not census_enabled():
        return None
    with _census_tick_lock:
        _census_tick[0] += 1
        due = _census_tick[0] % _CENSUS_EVERY == 1
    if not due:
        return None
    chk = leak_detector.check()
    return {"live_bytes": chk["census"]["total_bytes"],
            "leak_bytes": chk["growth_bytes"]}


def _crash_census() -> Dict[str, Any]:
    return buffer_census()


_telemetry.register_step_observer(_step_census_observer)
_telemetry.register_crash_section("buffer_census", _crash_census)
_telemetry.register_crash_section("programs", program_table)
