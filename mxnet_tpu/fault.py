"""Fault-tolerance primitives: retry policy + deterministic fault injection.

The failure posture (SURVEY §5.3, checkpoint.py docstring) is "fail fast
and restart from the last checkpoint" — but between "fast" and "fail"
there is a band of transient faults (a parameter-server restart, a
dropped TCP connection, a slow peer) that the reference absorbed inside
ps-lite's resender and that this rebuild must absorb itself.  This module
is the shared vocabulary for that band:

* :class:`RetryPolicy` — deadline + exponential backoff + jitter,
  env-tunable via ``MX_KVSTORE_RETRY_*``.  Used by the dist_async kvstore
  client (kvstore/kvstore.py) to survive server blips, and available to
  anything else that talks to a peer.

* :class:`FaultInjector` / :func:`inject` — a process-wide registry of
  armed faults keyed by *site* name.  Production code calls
  :func:`fire("kvstore.send")` at instrumented points (a no-op when the
  site is unarmed — one dict lookup); tests and ``tools/launch.py
  --fault`` arm rules that drop/delay/error deterministically on the
  n-th call.  Faults arm from the ``MX_FAULT_INJECT`` env spec too, so
  subprocess workers under the launcher misbehave on cue.

* Virtual time — ``use_virtual_time()`` swaps the module clock for a
  counter so chaos tests exercise full backoff schedules without real
  sleeps (tier-1 stays fast; the ``chaos`` pytest marker relies on it).

Instrumented sites (grep for ``fault.fire``):
  ``kvstore.send``        before each client RPC send
  ``kvstore.recv``        before each client RPC receive
  ``server.handle``       server-side, before dispatching a request
  ``kvstore.membership``  server-side, before applying a JOIN/LEAVE
                          membership mutation (elastic resize chaos)
  ``checkpoint.commit``   between checkpoint write and atomic rename
  ``module.fit.epoch``    end of each Module.fit epoch (pre-checkpoint)
  ``worker.step``         start of each fit-loop batch — what
                          ``launch.py --restart on-failure --fault
                          'worker.step:crash:after=N'`` supervisor chaos
                          runs kill into (``delay`` specs here model a
                          hang for the MX_STEP_TIMEOUT watchdog)
  ``serve.request``       serving replica, before handling each wire
                          request (``crash`` = kill a replica mid-load)
  ``serve.client.send``   serve client, before each RPC send
  ``serve.client.recv``   serve client, before each RPC receive
  ``router.request``      serve router (ISSUE 17), before handling each
                          inbound client envelope (``crash`` = kill the
                          router mid-load)
  ``router.forward``      serve router, before forwarding an envelope
                          to the chosen replica — error/close here
                          looks like a dead replica and must trigger a
                          router-side failover, never a double dispatch
"""
from __future__ import annotations

import random as _random
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from .base import get_env

__all__ = ["FaultError", "RetryPolicy", "FaultInjector", "inject", "fire",
           "clear", "site_calls", "arm_from_env", "use_virtual_time",
           "VirtualClock", "now", "sleep", "is_virtual", "Deadline"]


class FaultError(ConnectionError):
    """Raised by an armed ``error``/``close`` fault.  Subclasses
    ConnectionError so transport-level retry loops treat an injected
    fault exactly like a real dropped connection."""

    def __init__(self, site: str, action: str = "error"):
        super().__init__("injected fault at %r (action=%s)" % (site, action))
        self.site = site
        self.action = action


# ---------------------------------------------------------------------------
# Clock: real by default; virtual (counter-based) under use_virtual_time()
# so retry/backoff schedules run instantly in tests.
# ---------------------------------------------------------------------------

class VirtualClock:
    """Monotonic counter standing in for (time.monotonic, time.sleep)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()
        self.sleeps: List[float] = []   # log of requested sleeps (asserted on)

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        with self._lock:
            self._now += max(0.0, float(seconds))
            self.sleeps.append(float(seconds))

    def advance(self, seconds: float) -> None:
        self.sleep(seconds)


class _RealClock:
    # the one legitimate raw-clock site: this IS the injectable clock's
    # real backend
    now = staticmethod(_time.monotonic)    # mxlint: disable=wall-clock-in-fault-path
    sleep = staticmethod(_time.sleep)      # mxlint: disable=wall-clock-in-fault-path


_clock: Any = _RealClock()
_clock_lock = threading.Lock()


def now() -> float:
    return _clock.now()


def sleep(seconds: float) -> None:
    _clock.sleep(seconds)


def is_virtual() -> bool:
    """True while a use_virtual_time() context governs the module clock.
    Waits that cannot ride sleep() directly (condition variables, socket
    timeouts) branch on this to charge their tick to the virtual clock
    instead of blocking real time."""
    return isinstance(_clock, VirtualClock)


class Deadline:
    """A wait budget that survives clock-regime switches.

    ``now()``-anchored absolute deadlines break when a use_virtual_time()
    context starts or ends around a parked thread: a virtual anchor
    compared against real monotonic mis-fires by tens of thousands of
    seconds (either direction).  Deadline instead consumes elapsed time
    per same-regime segment — the interval spanning a switch is simply
    not charged — so long-lived waits (barrier parks, connect retries,
    drain loops) keep an honest budget on whichever clock is current.
    """

    __slots__ = ("_remaining", "_anchor", "_virtual")

    def __init__(self, seconds: float):
        self._remaining = float(seconds)
        self._anchor = now()
        self._virtual = is_virtual()

    def remaining(self) -> float:
        cur_virtual = is_virtual()
        cur = now()
        if cur_virtual == self._virtual:
            self._remaining -= max(0.0, cur - self._anchor)
        else:
            self._virtual = cur_virtual
        self._anchor = cur
        return self._remaining

    def expired(self) -> bool:
        return self.remaining() <= 0


class use_virtual_time:
    """Context manager: swap the module clock for a VirtualClock.

    ``with fault.use_virtual_time() as clk: ...`` — every RetryPolicy
    sleep inside advances ``clk`` instead of blocking; ``clk.sleeps``
    records the schedule for assertions.
    """

    def __init__(self, start: float = 0.0):
        self._vc = VirtualClock(start)
        self._saved = None

    def __enter__(self) -> VirtualClock:
        global _clock
        with _clock_lock:
            self._saved = _clock
            _clock = self._vc
        return self._vc

    def __exit__(self, *exc):
        global _clock
        with _clock_lock:
            _clock = self._saved
        return False


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Deadline-bounded exponential backoff with jitter.

    Delay for attempt k is ``min(base * 2**k, max_delay)`` plus uniform
    jitter in ``[0, jitter * delay]``; retries stop when the deadline
    (seconds from the first attempt) would be exceeded.  Defaults read
    the ``MX_KVSTORE_RETRY_{DEADLINE,BASE,MAX,JITTER}`` env knobs so a
    deployment can re-tune recovery without code changes.

    Usage::

        policy = RetryPolicy.from_env()
        for attempt in policy:           # yields 0, 1, 2, ... sleeping
            try:                         # between attempts
                return do_rpc()
            except ConnectionError as e:
                policy.note(e)           # remembered for the final raise
        raise MXNetError("gave up: %s" % policy.last_error)
    """

    def __init__(self, deadline: Optional[float] = None,
                 base: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 jitter: Optional[float] = None,
                 rng: Optional[_random.Random] = None):
        self.deadline = float(deadline if deadline is not None else
                              get_env("MX_KVSTORE_RETRY_DEADLINE",
                                      dtype=float))
        self.base = float(base if base is not None else
                          get_env("MX_KVSTORE_RETRY_BASE", dtype=float))
        self.max_delay = float(max_delay if max_delay is not None else
                               get_env("MX_KVSTORE_RETRY_MAX", dtype=float))
        self.jitter = float(jitter if jitter is not None else
                            get_env("MX_KVSTORE_RETRY_JITTER", dtype=float))
        self._rng = rng or _random.Random()
        self.last_error: Optional[BaseException] = None

    @classmethod
    def from_env(cls, **overrides) -> "RetryPolicy":
        return cls(**overrides)

    def delay(self, attempt: int) -> float:
        d = min(self.base * (2.0 ** attempt), self.max_delay)
        if self.jitter > 0:
            d += self._rng.uniform(0.0, self.jitter * d)
        return d

    def note(self, err: BaseException) -> None:
        self.last_error = err

    def __iter__(self):
        start = now()
        attempt = 0
        while True:
            yield attempt
            d = self.delay(attempt)
            if now() + d - start > self.deadline:
                return      # next attempt would blow the deadline
            sleep(d)
            attempt += 1


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

class _Rule:
    """One armed fault: fires on calls [after, after+count) at `site`."""

    __slots__ = ("site", "action", "after", "count", "delay", "exc",
                 "fired", "armed_at_call")

    def __init__(self, site, action, after, count, delay, exc):
        self.site = site
        self.action = action        # "error" | "close" | "delay" | "crash"
        self.after = int(after)     # skip this many calls first
        self.count = int(count)     # then fire this many times (-1 = forever)
        self.delay = float(delay)
        self.exc = exc
        self.fired = 0
        self.armed_at_call = None   # site call-counter when armed (lazy)

    def matches(self, nth_since_armed: int) -> bool:
        if nth_since_armed < self.after:
            return False
        if self.count >= 0 and self.fired >= self.count:
            return False
        return True


class FaultInjector:
    """Registry of armed fault rules, keyed by site name.

    Deterministic by construction: rules trigger on exact call ordinals
    (``after=n`` → skip n calls, then fire), never on probabilities, so
    a chaos test replays identically every run.  ``delay`` actions go
    through the module clock and therefore cost nothing under
    ``use_virtual_time()``.
    """

    def __init__(self):
        self._rules: Dict[str, List[_Rule]] = {}
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- arming -------------------------------------------------------------
    def inject(self, site: str, action: str = "error", after: int = 0,
               count: int = 1, delay: float = 0.0,
               exc: Optional[BaseException] = None) -> _Rule:
        if action not in ("error", "close", "delay", "crash"):
            raise ValueError("unknown fault action %r" % (action,))
        rule = _Rule(site, action, after, count, delay, exc)
        with self._lock:
            rule.armed_at_call = self._calls.get(site, 0)
            self._rules.setdefault(site, []).append(rule)
        return rule

    def disarm(self, rule: _Rule) -> None:
        with self._lock:
            rules = self._rules.get(rule.site, [])
            if rule in rules:
                rules.remove(rule)

    def clear(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._rules.clear()
                self._calls.clear()
            else:
                self._rules.pop(site, None)
                self._calls.pop(site, None)

    def site_calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)

    # -- firing -------------------------------------------------------------
    def fire(self, site: str, context: Any = None,
             on_close: Optional[Callable[[], None]] = None) -> None:
        """Call at an instrumented point.  No-op unless a rule matches.

        error  — raise FaultError (or the rule's custom exc)
        close  — run `on_close` (e.g. sock.close) then raise FaultError
        delay  — sleep `rule.delay` via the module clock, continue
        crash  — raise SystemExit (simulated process death; tests catch
                 it, subprocess workers genuinely die)
        """
        with self._lock:
            n = self._calls.get(site, 0)
            self._calls[site] = n + 1
            rules = self._rules.get(site)
            if not rules:
                return
            hit = None
            for rule in rules:
                if rule.matches(n - rule.armed_at_call):
                    rule.fired += 1
                    hit = rule
                    break
        if hit is None:
            return
        if hit.action == "delay":
            sleep(hit.delay)
            return
        if hit.action == "crash":
            raise SystemExit("injected crash at %r" % (site,))
        if hit.action == "close" and on_close is not None:
            try:
                on_close()
            except OSError:
                pass
        if hit.exc is not None:
            raise hit.exc
        raise FaultError(site, hit.action)


_default = FaultInjector()

# module-level convenience API (the spelling production code uses)
inject = _default.inject
fire = _default.fire
clear = _default.clear
disarm = _default.disarm
site_calls = _default.site_calls


def arm_from_env(spec: Optional[str] = None) -> List[_Rule]:
    """Arm rules from an ``MX_FAULT_INJECT`` spec string.

    Grammar: ``site:action[:key=val[,key=val...]]`` joined by ``;``.
    Keys: after, count, delay.  Example (what ``tools/launch.py
    --fault`` forwards to workers)::

        MX_FAULT_INJECT="kvstore.send:close:after=3;server.handle:delay:delay=0.5,count=2"
    """
    spec = spec if spec is not None else get_env("MX_FAULT_INJECT", "")
    rules = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError("bad MX_FAULT_INJECT entry %r "
                             "(want site:action[:k=v,...])" % (part,))
        site, action = fields[0], fields[1]
        kwargs: Dict[str, Any] = {}
        if len(fields) > 2 and fields[2]:
            for kv in fields[2].split(","):
                k, _, v = kv.partition("=")
                k = k.strip()
                if k not in ("after", "count", "delay"):
                    raise ValueError("bad MX_FAULT_INJECT key %r in %r"
                                     % (k, part))
                kwargs[k] = float(v) if k == "delay" else int(v)
        rules.append(inject(site, action=action, **kwargs))
    return rules


# arm automatically in any process launched with the env spec set
# (tools/launch.py --fault path); a bad spec should fail loudly at import
if get_env("MX_FAULT_INJECT", ""):
    arm_from_env()
