"""Gluon recurrent layers: RNN / LSTM / GRU over the fused RNN op.

Reference: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer, class RNN,
class LSTM, class GRU) — parameter naming l{i}_i2h_weight / r{i}_i2h_weight
(reverse direction) kept for checkpoint parity with the reference's
cuDNN-packed layout (ops/rnn.py docstring).
"""
from __future__ import annotations

from typing import List, Optional

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray, invoke
from ... import initializer as init_mod
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = _GATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in (["l", "r"] if bidirectional else ["l"]):
                self._register_param("%s%d_i2h_weight" % (j, i),
                                     (ng * nh, ni), i2h_weight_initializer)
                self._register_param("%s%d_h2h_weight" % (j, i),
                                     (ng * nh, nh), h2h_weight_initializer)
                self._register_param("%s%d_i2h_bias" % (j, i),
                                     (ng * nh,), i2h_bias_initializer)
                self._register_param("%s%d_h2h_bias" % (j, i),
                                     (ng * nh,), h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        if isinstance(init, str):
            init = init_mod.create(init)
        p = Parameter(name, shape=shape, init=init, allow_deferred_init=True)
        setattr(self, name, p)

    def infer_shape(self, inputs, *args):
        isz = inputs.shape[2] if self._layout == "TNC" else inputs.shape[-1]
        ng, nh = self._gates, self._hidden_size
        ni = isz
        for i in range(self._num_layers):
            for j in (["l", "r"] if self._dir == 2 else ["l"]):
                getattr(self, "%s%d_i2h_weight" % (j, i)).shape = (ng * nh, ni)
            ni = nh * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (reference: _RNNLayer.begin_state)."""
        states = []
        for info in self.state_info(batch_size):
            states.append(nd.zeros(**info, **kwargs) if func is None
                          else func(**info, **kwargs))
        return states

    def _pack_params(self, ctx):
        """Concatenate per-layer params into the cuDNN-layout flat vector
        (ops/rnn.py) — weights for all layers/directions, then biases."""
        flat = []
        dirs = ["l", "r"] if self._dir == 2 else ["l"]
        for i in range(self._num_layers):
            for j in dirs:
                flat.append(getattr(self, "%s%d_i2h_weight" % (j, i))
                            .data(ctx).reshape(-1))
                flat.append(getattr(self, "%s%d_h2h_weight" % (j, i))
                            .data(ctx).reshape(-1))
        for i in range(self._num_layers):
            for j in dirs:
                flat.append(getattr(self, "%s%d_i2h_bias" % (j, i)).data(ctx))
                flat.append(getattr(self, "%s%d_h2h_bias" % (j, i)).data(ctx))
        return nd.concat(*flat, dim=0)

    def forward(self, inputs, states=None, sequence_length=None):
        from ... import autograd
        ctx = inputs.context
        batch_size = inputs.shape[0 if self._layout == "NTC" else 1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=ctx)
        if isinstance(states, NDArray):
            states = [states]
        if self._layout == "NTC":
            inputs = inputs.transpose((1, 0, 2))
        params = self._pack_params(ctx)
        h0 = states[0]
        c0 = states[1] if len(states) > 1 else None
        out, h_out, c_out = invoke(
            "RNN", inputs, params, h0, c0, sequence_length,
            state_size=self._hidden_size, num_layers=self._num_layers,
            mode=self._mode, bidirectional=self._dir == 2, p=self._dropout,
            use_sequence_length=sequence_length is not None,
            training=autograd.is_training())
        if self._layout == "NTC":
            out = out.transpose((1, 0, 2))
        new_states = [h_out] if self._mode != "lstm" else [h_out, c_out]
        if skip_states:
            return out
        return out, new_states

    def __repr__(self):
        return "%s(%s, %s, layers=%s%s)" % (
            type(self).__name__, self._input_size or "?", self._hidden_size,
            self._num_layers, ", bidirectional" if self._dir == 2 else "")


class RNN(_RNNLayer):
    """Elman RNN with tanh/relu (reference: gluon.rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]


class LSTM(_RNNLayer):
    """Reference: gluon.rnn.LSTM (cuDNN-RNN parity; SURVEY.md M5)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape}, {"shape": shape}]


class GRU(_RNNLayer):
    """Reference: gluon.rnn.GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]
