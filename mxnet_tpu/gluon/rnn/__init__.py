"""Gluon recurrent API (reference: python/mxnet/gluon/rnn/)."""
from .rnn_layer import RNN, LSTM, GRU
from .rnn_cell import (RecurrentCell, HybridRecurrentCell, RNNCell, LSTMCell,
                       GRUCell, SequentialRNNCell, HybridSequentialRNNCell,
                       DropoutCell, ModifierCell, BidirectionalCell,
                       ResidualCell, ZoneoutCell)

__all__ = ["RNN", "LSTM", "GRU", "RecurrentCell", "HybridRecurrentCell",
           "RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "HybridSequentialRNNCell", "DropoutCell", "ModifierCell",
           "BidirectionalCell", "ResidualCell", "ZoneoutCell"]
