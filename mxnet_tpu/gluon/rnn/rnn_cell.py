"""Recurrent cells (explicit unrolled variants).

Reference: python/mxnet/gluon/rnn/rnn_cell.py (RecurrentCell, RNNCell,
LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, BidirectionalCell,
ResidualCell, ZoneoutCell; rnn_cell unroll semantics).

Gate order matches the fused op (ops/rnn.py): LSTM i,f,g,o; GRU r,z,n —
a cell unroll and the fused `RNN` op produce identical numbers, the
reference's test_gluon_rnn consistency contract.
"""
from __future__ import annotations

from typing import List, Optional

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray, invoke
from ... import initializer as init_mod
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "BidirectionalCell",
           "ResidualCell", "ZoneoutCell", "ModifierCell", "HybridSequentialRNNCell"]


class RecurrentCell(HybridBlock):
    """Base cell (reference: rnn.RecurrentCell)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(nd.zeros(**info, **kwargs) if func is None
                          else func(**info, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll for `length` steps (reference: RecurrentCell.unroll)."""
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, NDArray):
            batch_size = inputs.shape[batch_axis]
            seq = [inputs.slice_axis(axis, i, i + 1).squeeze(axis)
                   for i in range(length)]
        else:
            seq = list(inputs)
            batch_size = seq[0].shape[0]
        if begin_state is None:
            begin_state = self.begin_state(batch_size, ctx=seq[0].context)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(seq[i], states)
            outputs.append(output)
        if valid_length is not None:
            masked = []
            for i, out in enumerate(outputs):
                mask = (valid_length > i).astype(out.dtype)
                masked.append(out * mask.reshape((-1,) + (1,) * (out.ndim - 1)))
            outputs = masked
        if merge_outputs or merge_outputs is None and isinstance(inputs, NDArray):
            outputs = nd.stack(outputs, axis=axis)
        return outputs, states


HybridRecurrentCell = RecurrentCell


class RNNCell(RecurrentCell):
    """Elman cell (reference: rnn.RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(hidden_size, input_size),
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(hidden_size, hidden_size))
        self.i2h_bias = Parameter("i2h_bias", shape=(hidden_size,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(hidden_size,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def forward(self, inputs, states):
        ctx = inputs.context
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(ctx),
                     self.i2h_bias.data(ctx), num_hidden=self._hidden_size)
        h2h = invoke("FullyConnected", states[0], self.h2h_weight.data(ctx),
                     self.h2h_bias.data(ctx), num_hidden=self._hidden_size)
        output = invoke("Activation", i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """Reference: rnn.LSTMCell — gates i,f,g,o."""

    def __init__(self, hidden_size, input_size=0,
                 activation="tanh", recurrent_activation="sigmoid", **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        nh = hidden_size
        self.i2h_weight = Parameter("i2h_weight", shape=(4 * nh, input_size),
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(4 * nh, nh))
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * nh,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * nh,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def forward(self, inputs, states):
        ctx = inputs.context
        nh = self._hidden_size
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(ctx),
                     self.i2h_bias.data(ctx), num_hidden=4 * nh)
        h2h = invoke("FullyConnected", states[0], self.h2h_weight.data(ctx),
                     self.h2h_bias.data(ctx), num_hidden=4 * nh)
        gates = i2h + h2h
        slices = gates.split(num_outputs=4, axis=1)
        in_gate = slices[0].sigmoid()
        forget_gate = slices[1].sigmoid()
        in_transform = slices[2].tanh()
        out_gate = slices[3].sigmoid()
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * next_c.tanh()
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """Reference: rnn.GRUCell — gates r,z,n."""

    def __init__(self, hidden_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        nh = hidden_size
        self.i2h_weight = Parameter("i2h_weight", shape=(3 * nh, input_size),
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(3 * nh, nh))
        self.i2h_bias = Parameter("i2h_bias", shape=(3 * nh,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(3 * nh,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def forward(self, inputs, states):
        ctx = inputs.context
        nh = self._hidden_size
        prev_h = states[0]
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(ctx),
                     self.i2h_bias.data(ctx), num_hidden=3 * nh)
        h2h = invoke("FullyConnected", prev_h, self.h2h_weight.data(ctx),
                     self.h2h_bias.data(ctx), num_hidden=3 * nh)
        i2h_r, i2h_z, i2h_n = i2h.split(num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = h2h.split(num_outputs=3, axis=1)
        reset = (i2h_r + h2h_r).sigmoid()
        update = (i2h_z + h2h_z).sigmoid()
        next_h_tmp = (i2h_n + reset * h2h_n).tanh()
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: rnn.SequentialRNNCell)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[pos:pos + n]
            pos += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


class DropoutCell(RecurrentCell):
    """Reference: rnn.DropoutCell."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        from ... import autograd
        if self._rate > 0 and autograd.is_training():
            inputs = invoke("Dropout", inputs, p=self._rate,
                            axes=tuple(self._axes), mode="training")
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ResidualCell(ModifierCell):
    """Reference: rnn.ResidualCell — output += input."""

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class ZoneoutCell(ModifierCell):
    """Reference: rnn.ZoneoutCell — stochastically preserve prev states."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import autograd
        next_output, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states

        def mask(p, like):
            return invoke("_random_bernoulli", prob=1 - p, shape=like.shape,
                          dtype=str(like.dtype))

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = nd.zeros(next_output.shape,
                                   ctx=next_output.context)
        if self.zoneout_outputs > 0:
            m = mask(self.zoneout_outputs, next_output)
            output = m * next_output + (1 - m) * prev_output
        else:
            output = next_output
        if self.zoneout_states > 0:
            new_states = []
            for new_s, old_s in zip(next_states, states):
                m = mask(self.zoneout_states, new_s)
                new_states.append(m * new_s + (1 - m) * old_s)
        else:
            new_states = next_states
        self._prev_output = output
        return output, new_states


class HybridSequentialRNNCell(SequentialRNNCell):
    """Hybrid-capable sequential stack (reference: rnn/rnn_cell.py
    HybridSequentialRNNCell).  Cells here are HybridBlocks already, so
    the stacking semantics are SequentialRNNCell's; the distinct class
    keeps reference API parity (isinstance checks, repr)."""


class BidirectionalCell(RecurrentCell):
    """Reference: rnn.BidirectionalCell — unroll-only."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def forward(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if isinstance(inputs, NDArray):
            batch_size = inputs.shape[batch_axis]
            seq = [inputs.slice_axis(axis, i, i + 1).squeeze(axis)
                   for i in range(length)]
        else:
            seq = list(inputs)
            batch_size = seq[0].shape[0]
        l_cell, r_cell = self._children.values()
        if begin_state is None:
            begin_state = self.begin_state(batch_size, ctx=seq[0].context)
        n_l = len(l_cell.state_info())
        l_outputs, l_states = l_cell.unroll(
            length, seq, begin_state[:n_l], layout, merge_outputs=False,
            valid_length=valid_length)
        if valid_length is None:
            rev_seq = list(reversed(seq))
        else:
            # per-sample reverse of the valid region (reference:
            # SequenceReverse(use_sequence_length=True)) so the reverse
            # cell starts from each sequence's last valid step
            stacked = nd.stack(seq, axis=0)  # (T, N, C)
            rev = invoke("SequenceReverse", stacked, valid_length,
                         use_sequence_length=True)
            rev_seq = [rev[t] for t in range(length)]
        r_outputs, r_states = r_cell.unroll(
            length, rev_seq, begin_state[n_l:], layout,
            merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            r_outputs = list(reversed(r_outputs))
        else:
            r_stacked = nd.stack(r_outputs, axis=0)
            r_rev = invoke("SequenceReverse", r_stacked, valid_length,
                           use_sequence_length=True)
            r_outputs = [r_rev[t] for t in range(length)]
        outputs = [nd.concat(lo, ro, dim=1) for lo, ro in
                   zip(l_outputs, r_outputs)]
        if merge_outputs or merge_outputs is None:
            outputs = nd.stack(outputs, axis=axis)
        return outputs, l_states + r_states
