"""Pretrained-weight store (reference: python/mxnet/gluon/model_zoo/
model_store.py — get_model_file with a download cache).

No network egress in this environment, so the store is purely local: a
weight drop at ``$MX_PRETRAINED_DIR`` (or ``~/.mxnet/models``, the
reference's cache root) activates ``get_model(name, pretrained=True)``
without code changes.  Accepted layouts per model name, in priority
order:

    <root>/<name>-<sha1[:8]>.params   (the reference's cache naming —
                                       the 8-hex short hash MUST match
                                       the file content's sha1 prefix,
                                       the reference's check_sha1 gate)
    <root>/<name>.params
    <root>/<name>-0000.params         (reference checkpoint naming)

Absent weights raise the same clear error everywhere, pointing at the
drop location — the API stays wired so data arrival is a no-op change
(VERDICT r3 missing #8).
"""
from __future__ import annotations

import glob as _glob
import hashlib
import os
import re

from ...base import get_env

__all__ = ["get_model_file", "load_pretrained", "purge"]

_SHA1_NAME = re.compile(r"-([0-9a-f]{8})\.params$")


def _root(root=None):
    return root or get_env("MX_PRETRAINED_DIR", default="") or \
        os.path.join(os.path.expanduser("~"), ".mxnet", "models")


def get_model_file(name: str, root=None) -> str:
    """Path of `name`'s local weight file (reference: get_model_file —
    minus the download; raises with the expected drop location).
    Reference-style sha1-named cache files are integrity-checked: the
    short hash embedded in the file name must be a prefix of the file
    content's sha1 (reference: gluon.utils.check_sha1)."""
    base = _root(root)
    corrupted = []
    for cand in sorted(_glob.glob(
            os.path.join(base, name + "-????????.params"))):
        m = _SHA1_NAME.search(cand)
        if not m:
            continue  # e.g. <name>-0000.params: checkpoint naming, below
        sha1 = hashlib.sha1()
        with open(cand, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                sha1.update(chunk)
        if sha1.hexdigest().startswith(m.group(1)):
            return cand
        corrupted.append(cand)
    for cand in (os.path.join(base, name + ".params"),
                 os.path.join(base, name + "-0000.params")):
        if os.path.exists(cand):
            return cand
    if corrupted:
        # only fatal when no valid fallback exists: a stale corrupted
        # cache file must not shadow a good flat-named drop
        raise OSError(
            "pretrained weight file(s) %s failed the sha1 short-hash "
            "check embedded in their names — the drop is corrupted or "
            "misnamed; re-drop or rename without the 8-hex suffix"
            % corrupted)
    raise FileNotFoundError(
        "pretrained weights for %r not found; this environment has no "
        "network egress — drop %s.params (or the reference cache file "
        "%s-<sha1[:8]>.params) into %s (or set MX_PRETRAINED_DIR) to "
        "activate pretrained=True" % (name, name, name, base))


def purge(root=None):
    """Reference: model_store.purge — clear the local weight cache."""
    base = _root(root)
    for f in _glob.glob(os.path.join(base, "*.params")):
        os.remove(f)


def load_pretrained(net, name: str, root=None, ctx=None):
    """Load `name`'s local weights into `net` (the pretrained=True path
    of every model_zoo builder)."""
    path = get_model_file(name, root)
    net.load_parameters(path, ctx=ctx, allow_missing=False,
                        ignore_extra=False)
    return net
