"""Pretrained-weight store (reference: python/mxnet/gluon/model_zoo/
model_store.py — get_model_file with a download cache).

No network egress in this environment, so the store is purely local: a
weight drop at ``$MX_PRETRAINED_DIR`` (or ``~/.mxnet/models``, the
reference's cache root) activates ``get_model(name, pretrained=True)``
without code changes.  Accepted layouts per model name:

    <root>/<name>.params
    <root>/<name>-0000.params      (reference checkpoint naming)

Absent weights raise the same clear error everywhere, pointing at the
drop location — the API stays wired so data arrival is a no-op change
(VERDICT r3 missing #8).
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "load_pretrained"]


def _root(root=None):
    return root or os.environ.get("MX_PRETRAINED_DIR") or \
        os.path.join(os.path.expanduser("~"), ".mxnet", "models")


def get_model_file(name: str, root=None) -> str:
    """Path of `name`'s local weight file (reference: get_model_file —
    minus the download; raises with the expected drop location)."""
    base = _root(root)
    for cand in (os.path.join(base, name + ".params"),
                 os.path.join(base, name + "-0000.params")):
        if os.path.exists(cand):
            return cand
    raise FileNotFoundError(
        "pretrained weights for %r not found; this environment has no "
        "network egress — drop %s.params into %s (or set "
        "MX_PRETRAINED_DIR) to activate pretrained=True"
        % (name, name, base))


def load_pretrained(net, name: str, root=None, ctx=None):
    """Load `name`'s local weights into `net` (the pretrained=True path
    of every model_zoo builder)."""
    path = get_model_file(name, root)
    net.load_parameters(path, ctx=ctx, allow_missing=False,
                        ignore_extra=False)
    return net
