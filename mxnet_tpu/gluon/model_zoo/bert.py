"""BERT model family (GluonNLP-compatible architecture).

Reference: the reference repo pairs with GluonNLP's
gluonnlp/model/bert.py (BERTModel, BERTEncoder, BERTLayerNorm,
bert_12_768_12 / bert_24_1024_16) built on the fused transformer ops of
src/operator/contrib/transformer.cc — BASELINE config 2 (BERT-base
pretraining, data-parallel kvstore='ici').

TPU-native: attention dispatches to the Pallas flash kernel via the
multi_head_attention op (ops/attention.py); bf16 via net.cast; the whole
encoder hybridizes into one XLA program.  The pod-scale DP/TP path jits
the training step over a Mesh (parallel.TrainStep — attention TP shards
heads, FFN shards the hidden dim).
"""
from __future__ import annotations

import math

from ...ndarray.ndarray import NDArray, invoke
from ... import ndarray as nd
from .. import nn
from ..block import HybridBlock

__all__ = ["BERTModel", "BERTEncoder", "BERTEncoderLayer", "MultiHeadAttention",
           "PositionwiseFFN", "bert_12_768_12", "bert_24_1024_16", "get_bert"]


class MultiHeadAttention(HybridBlock):
    """Self/cross attention with fused QKV projection (reference: the
    interleaved_matmul_selfatt ops; GluonNLP DotProductSelfAttentionCell)."""

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self.query_key_value = nn.Dense(3 * units, flatten=False,
                                        use_bias=use_bias)
        self.proj = nn.Dense(units, flatten=False, use_bias=use_bias)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        # x: (N, T, C)
        qkv = self.query_key_value(x)
        q, k, v = qkv.split(num_outputs=3, axis=-1)
        out = invoke("multi_head_attention", q, k, v, mask,
                     num_heads=self._num_heads, scaled=True,
                     units=self._units)
        return self.dropout(self.proj(out))


class PositionwiseFFN(HybridBlock):
    """Reference: GluonNLP PositionwiseFFN (gelu for BERT)."""

    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        self.ffn_1 = nn.Dense(hidden_size, flatten=False)
        self.activation = nn.GELU() if activation == "gelu" else \
            nn.Activation(activation)
        self.ffn_2 = nn.Dense(units, flatten=False)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        return self.dropout(self.ffn_2(self.activation(self.ffn_1(x))))


class BERTEncoderLayer(HybridBlock):
    """Post-LN transformer layer (BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self.attention = MultiHeadAttention(units, num_heads, dropout)
        self.layer_norm_att = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout)
        self.layer_norm_ffn = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout)

    def forward(self, x, mask=None):
        att = self.attention(x, mask)
        x = self.layer_norm_att(x + att)
        ffn = self.ffn(x)
        return self.layer_norm_ffn(x + ffn)


class BERTEncoder(HybridBlock):
    """Stack of encoder layers (reference: GluonNLP BERTEncoder)."""

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 max_length=512, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._max_length = max_length
        self._units = units
        self.position_weight = None  # owned by BERTModel embeddings
        self.transformer_cells = nn.HybridSequential()
        for _ in range(num_layers):
            self.transformer_cells.add(
                BERTEncoderLayer(units, hidden_size, num_heads, dropout))

    def forward(self, x, mask=None):
        for cell in self.transformer_cells:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT with MLM + NSP heads (reference: GluonNLP BERTModel).

    forward(inputs, token_types, valid_length=None) →
      (sequence_output, pooled_output) — use_decoder adds mlm_logits,
      use_classifier adds nsp_logits, matching GluonNLP's output tuple.
    """

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, vocab_size=30522, token_type_vocab_size=2,
                 max_length=512, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(token_type_vocab_size, units)
        self.position_embed = nn.Embedding(max_length, units)
        self.embed_layer_norm = nn.LayerNorm(in_channels=units)
        self.embed_dropout = nn.Dropout(dropout)
        self.encoder = BERTEncoder(num_layers, units, hidden_size, num_heads,
                                   max_length, dropout)
        self.use_pooler = use_pooler
        self.use_decoder = use_decoder
        self.use_classifier = use_classifier
        if use_pooler:
            self.pooler = nn.Dense(units, activation="tanh", flatten=False)
        if use_decoder:
            # MLM head: transform + tied-weight output over vocab
            self.decoder_transform = nn.Dense(units, flatten=False)
            self.decoder_act = nn.GELU()
            self.decoder_norm = nn.LayerNorm(in_channels=units)
            self.decoder_out = nn.Dense(vocab_size, flatten=False)
        if use_classifier:
            self.classifier = nn.Dense(2, flatten=False)

    def _attention_mask(self, valid_length, seq_len):
        if valid_length is None:
            return None
        steps = nd.arange(seq_len, ctx=valid_length.context)
        mask = invoke("broadcast_lesser",
                      steps.reshape((1, 1, 1, seq_len)),
                      valid_length.reshape((-1, 1, 1, 1)))
        return mask

    def forward(self, inputs, token_types=None, valid_length=None):
        N, T = inputs.shape
        ctx = inputs.context
        positions = nd.arange(T, ctx=ctx)
        emb = self.word_embed(inputs)
        if token_types is not None:
            emb = emb + self.token_type_embed(token_types)
        emb = emb + self.position_embed(positions).reshape((1, T, self._units))
        emb = self.embed_dropout(self.embed_layer_norm(emb))
        mask = self._attention_mask(valid_length, T)
        seq_out = self.encoder(emb, mask)
        outputs = [seq_out]
        if self.use_pooler:
            pooled = self.pooler(seq_out[:, 0, :].reshape((N, self._units)))
            outputs.append(pooled)
            if self.use_classifier:
                outputs.append(self.classifier(pooled))
        if self.use_decoder:
            h = self.decoder_norm(self.decoder_act(
                self.decoder_transform(seq_out)))
            outputs.append(self.decoder_out(h))
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


# GluonNLP-style spec names: bert_{layers}_{units}_{heads}
def get_bert(num_layers, units, num_heads, **kwargs):
    return BERTModel(num_layers=num_layers, units=units,
                     hidden_size=4 * units, num_heads=num_heads, **kwargs)


def bert_12_768_12(**kwargs):
    """BERT-base (reference: gluonnlp bert_12_768_12)."""
    return get_bert(12, 768, 12, **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large (reference: gluonnlp bert_24_1024_16)."""
    return get_bert(24, 1024, 16, **kwargs)
