"""DenseNet 121/161/169/201 (reference: python/mxnet/gluon/model_zoo/
vision/densenet.py — _make_dense_block, _make_transition, DenseNet)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from .... import ndarray as nd

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201"]


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, kernel_size=1,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, kernel_size=3, padding=1,
                                use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def forward(self, x):
        return nd.concat(x, self.body(x), dim=1)


class _Transition(HybridBlock):
    def __init__(self, num_output_features, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(num_output_features, kernel_size=1,
                                use_bias=False))
        self.body.add(nn.AvgPool2D(pool_size=2, strides=2))

    def forward(self, x):
        return self.body(x)


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, kernel_size=7,
                                    strides=2, padding=3, use_bias=False))
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            block = nn.HybridSequential()
            for _ in range(num_layers):
                block.add(_DenseLayer(growth_rate, bn_size, dropout))
            self.features.add(block)
            num_features = num_features + num_layers * growth_rate
            if i != len(block_config) - 1:
                self.features.add(_Transition(num_features // 2))
                num_features = num_features // 2
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


# (init_features, growth_rate, block_config) — reference densenet_spec
densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


def _get(num_layers, pretrained, **kwargs):
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "densenet%d" % num_layers, root, ctx)
    init_f, growth, config = densenet_spec[num_layers]
    return DenseNet(init_f, growth, config, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _get(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _get(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _get(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _get(201, pretrained, **kwargs)
