"""MobileNet v1 / v2.

Reference: python/mxnet/gluon/model_zoo/vision/mobilenet.py (MobileNet,
MobileNetV2, LinearBottleneck, mobilenet0_25 … mobilenet1_0,
mobilenet_v2_1_0 …).  Depthwise conv = grouped `lax.conv_general_dilated`
with feature_group_count=channels (XLA lowers it onto the MXU).
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["MobileNet", "MobileNetV2", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25",
           "get_mobilenet", "get_mobilenet_v2"]


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(nn.HybridLambda(lambda x: x.clip(0, 6)) if relu6
                else nn.Activation("relu"))


def _add_conv_dw(out, dw_channels, channels, stride, relu6=False):
    _add_conv(out, dw_channels, kernel=3, stride=stride, pad=1,
              num_group=dw_channels, relu6=relu6)
    _add_conv(out, channels, relu6=relu6)


class LinearBottleneck(HybridBlock):
    """Reference: LinearBottleneck (expand → depthwise → project)."""

    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        self.out = nn.HybridSequential()
        _add_conv(self.out, in_channels * t, relu6=True)
        _add_conv(self.out, in_channels * t, kernel=3, stride=stride, pad=1,
                  num_group=in_channels * t, relu6=True)
        _add_conv(self.out, channels, active=False, relu6=True)

    def forward(self, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), kernel=3, stride=2,
                  pad=1)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2] * 3 + [1] * 5 + [2, 1]
        for dwc, c, s in zip(dw_channels, channels, strides):
            _add_conv_dw(self.features, dwc, c, s)
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        _add_conv(self.features, int(32 * multiplier), kernel=3, stride=2,
                  pad=1, relu6=True)
        in_channels_group = [int(x * multiplier) for x in
                             [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4 +
                             [96] * 3 + [160] * 3]
        channels_group = [int(x * multiplier) for x in
                          [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3 +
                          [160] * 3 + [320]]
        ts = [1] + [6] * 16
        strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
        for in_c, c, t, s in zip(in_channels_group, channels_group, ts,
                                 strides):
            self.features.add(LinearBottleneck(in_channels=in_c, channels=c,
                                               t=t, stride=s))
        last_channels = int(1280 * multiplier) if multiplier > 1.0 else 1280
        _add_conv(self.features, last_channels, relu6=True)
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, use_bias=False),
                        nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def get_mobilenet(multiplier, pretrained=False, ctx=None, root=None, **kwargs):
    net = MobileNet(multiplier, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "mobilenet%s" % str(multiplier).replace(
            ".", "_"), root, ctx)
    return net


def get_mobilenet_v2(multiplier, pretrained=False, ctx=None, root=None,
                     **kwargs):
    net = MobileNetV2(multiplier, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "mobilenetv2_%s" % str(multiplier).replace(
            ".", "_"), root, ctx)
    return net


def mobilenet1_0(**kwargs):
    return get_mobilenet(1.0, **kwargs)


def mobilenet0_75(**kwargs):
    return get_mobilenet(0.75, **kwargs)


def mobilenet0_5(**kwargs):
    return get_mobilenet(0.5, **kwargs)


def mobilenet0_25(**kwargs):
    return get_mobilenet(0.25, **kwargs)


def mobilenet_v2_1_0(**kwargs):
    return get_mobilenet_v2(1.0, **kwargs)


def mobilenet_v2_0_75(**kwargs):
    return get_mobilenet_v2(0.75, **kwargs)


def mobilenet_v2_0_5(**kwargs):
    return get_mobilenet_v2(0.5, **kwargs)


def mobilenet_v2_0_25(**kwargs):
    return get_mobilenet_v2(0.25, **kwargs)
