"""AlexNet (reference: python/mxnet/gluon/model_zoo/vision/alexnet.py)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["AlexNet", "alexnet"]


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(64, kernel_size=11, strides=4, padding=2,
                                    activation="relu"))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(nn.Conv2D(192, kernel_size=5, padding=2,
                                    activation="relu"))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(nn.Conv2D(384, kernel_size=3, padding=1,
                                    activation="relu"))
        self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                    activation="relu"))
        self.features.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                    activation="relu"))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root=None, **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "alexnet", root, ctx)
    return net
