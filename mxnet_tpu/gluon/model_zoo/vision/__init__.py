"""Model zoo: vision models (reference: python/mxnet/gluon/model_zoo/
vision/__init__.py — get_model name registry)."""
# modules first (star-imports below rebind some of these names to the
# model-constructor functions, e.g. `alexnet`)
from . import (resnet, alexnet as _alexnet_mod, vgg, mobilenet, squeezenet,
               densenet, inception)
from .resnet import *       # noqa: F401,F403
from .alexnet import *      # noqa: F401,F403
from .vgg import *          # noqa: F401,F403
from .mobilenet import *    # noqa: F401,F403
from .squeezenet import *   # noqa: F401,F403
from .densenet import *     # noqa: F401,F403
from .inception import *    # noqa: F401,F403

_models = {
    "resnet18_v1": resnet.resnet18_v1, "resnet34_v1": resnet.resnet34_v1,
    "resnet50_v1": resnet.resnet50_v1, "resnet101_v1": resnet.resnet101_v1,
    "resnet152_v1": resnet.resnet152_v1,
    "resnet18_v2": resnet.resnet18_v2, "resnet34_v2": resnet.resnet34_v2,
    "resnet50_v2": resnet.resnet50_v2, "resnet101_v2": resnet.resnet101_v2,
    "resnet152_v2": resnet.resnet152_v2,
    "vgg11": vgg.vgg11, "vgg13": vgg.vgg13, "vgg16": vgg.vgg16,
    "vgg19": vgg.vgg19, "vgg11_bn": vgg.vgg11_bn, "vgg13_bn": vgg.vgg13_bn,
    "vgg16_bn": vgg.vgg16_bn, "vgg19_bn": vgg.vgg19_bn,
    "alexnet": _alexnet_mod.alexnet,
    "densenet121": densenet.densenet121, "densenet161": densenet.densenet161,
    "densenet169": densenet.densenet169, "densenet201": densenet.densenet201,
    "squeezenet1.0": squeezenet.squeezenet1_0,
    "squeezenet1.1": squeezenet.squeezenet1_1,
    "inceptionv3": inception.inception_v3,
    "mobilenet1.0": mobilenet.mobilenet1_0,
    "mobilenet0.75": mobilenet.mobilenet0_75,
    "mobilenet0.5": mobilenet.mobilenet0_5,
    "mobilenet0.25": mobilenet.mobilenet0_25,
    "mobilenetv2_1.0": mobilenet.mobilenet_v2_1_0,
    "mobilenetv2_0.75": mobilenet.mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet.mobilenet_v2_0_5,
    "mobilenetv2_0.25": mobilenet.mobilenet_v2_0_25,
}


def get_model(name, **kwargs):
    """Reference: vision.get_model — model by registry name."""
    name = name.lower()
    if name not in _models:
        raise ValueError("Model %s is not supported. Available: %s"
                         % (name, sorted(_models.keys())))
    return _models[name](**kwargs)
