"""ResNet v1/v2 model family.

Reference: python/mxnet/gluon/model_zoo/vision/resnet.py (BasicBlockV1,
BottleneckV1, BasicBlockV2, BottleneckV2, ResNetV1, ResNetV2, resnet18_v1 …
resnet152_v2, get_resnet).

TPU-native notes: NCHW stays at the API; XLA lays out for the MXU.  The
whole network hybridizes into one XLA program — BN+ReLU fuse into the conv
epilogues, so the v1.b "fused residual" tricks the reference needed are
implicit.  bf16 training works by net.cast('bfloat16') + AMP policy.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    """Pre-activation-free residual block (reference: BasicBlockV1)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return (x + residual).relu()


class BottleneckV1(HybridBlock):
    """1x1-3x3-1x1 bottleneck (reference: BottleneckV1)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential()
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return (x + residual).relu()


class BasicBlockV2(HybridBlock):
    """Pre-activation residual block (reference: BasicBlockV2, He 2016)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x).relu()
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x).relu()
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x).relu()
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x).relu()
        x = self.conv2(x)
        x = self.bn3(x).relu()
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """Reference: class ResNetV1 (thumbnail=True uses the CIFAR 3x3 stem)."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(self._make_layer(block, num_layer,
                                               channels[i + 1], stride,
                                               in_channels=channels[i]))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=channels[-1])

    @staticmethod
    def _make_layer(block, layers, channels, stride, in_channels=0):
        layer = nn.HybridSequential()
        layer.add(block(channels, stride, channels != in_channels,
                        in_channels=in_channels))
        for _ in range(layers - 1):
            layer.add(block(channels, 1, False, in_channels=channels))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    """Reference: class ResNetV2."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        self.features = nn.HybridSequential()
        self.features.add(nn.BatchNorm(scale=False, center=False))
        if thumbnail:
            self.features.add(_conv3x3(channels[0], 1, 0))
        else:
            self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
        in_channels = channels[0]
        for i, num_layer in enumerate(layers):
            stride = 1 if i == 0 else 2
            self.features.add(ResNetV1._make_layer(
                block, num_layer, channels[i + 1], stride,
                in_channels=in_channels))
            in_channels = channels[i + 1]
        self.features.add(nn.BatchNorm())
        self.features.add(nn.Activation("relu"))
        self.features.add(nn.GlobalAvgPool2D())
        self.output = nn.Dense(classes, in_units=in_channels)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


# block type / layer spec tables (reference: resnet_spec)
resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    """Reference: get_resnet.  pretrained=True requires local weight files
    (no network egress); use net.load_parameters explicitly."""
    assert num_layers in resnet_spec, \
        "Invalid number of layers: %d. Options are %s" % (
            num_layers, str(sorted(resnet_spec.keys())))
    block_type, layers, channels = resnet_spec[num_layers]
    assert 1 <= version <= 2
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "resnet%d_v%d" % (num_layers, version), root,
                        ctx)
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
