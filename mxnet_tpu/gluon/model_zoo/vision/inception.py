"""Inception V3 (reference: python/mxnet/gluon/model_zoo/vision/
inception.py — _make_A/B/C/D/E branches, Inception3)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from .... import ndarray as nd

__all__ = ["Inception3", "inception_v3"]


def _conv(channels, kernel_size, strides=1, padding=0):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(channels, kernel_size, strides, padding,
                      use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branches(HybridBlock):
    """Run branches on one input and concat channel-wise."""

    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        for i, b in enumerate(branches):
            self.register_child(b, str(i))

    def forward(self, x):
        return nd.concat(*[b(x) for b in self._children.values()], dim=1)


def _seq(*blocks):
    out = nn.HybridSequential()
    out.add(*blocks)
    return out


def _make_A(pool_features):
    return _Branches([
        _conv(64, 1),
        _seq(_conv(48, 1), _conv(64, 5, padding=2)),
        _seq(_conv(64, 1), _conv(96, 3, padding=1), _conv(96, 3, padding=1)),
        _seq(nn.AvgPool2D(pool_size=3, strides=1, padding=1),
             _conv(pool_features, 1)),
    ])


def _make_B():
    return _Branches([
        _conv(384, 3, strides=2),
        _seq(_conv(64, 1), _conv(96, 3, padding=1), _conv(96, 3, strides=2)),
        _seq(nn.MaxPool2D(pool_size=3, strides=2)),
    ])


def _make_C(channels_7x7):
    c = channels_7x7
    return _Branches([
        _conv(192, 1),
        _seq(_conv(c, 1), _conv(c, (1, 7), padding=(0, 3)),
             _conv(192, (7, 1), padding=(3, 0))),
        _seq(_conv(c, 1), _conv(c, (7, 1), padding=(3, 0)),
             _conv(c, (1, 7), padding=(0, 3)),
             _conv(c, (7, 1), padding=(3, 0)),
             _conv(192, (1, 7), padding=(0, 3))),
        _seq(nn.AvgPool2D(pool_size=3, strides=1, padding=1), _conv(192, 1)),
    ])


def _make_D():
    return _Branches([
        _seq(_conv(192, 1), _conv(320, 3, strides=2)),
        _seq(_conv(192, 1), _conv(192, (1, 7), padding=(0, 3)),
             _conv(192, (7, 1), padding=(3, 0)), _conv(192, 3, strides=2)),
        _seq(nn.MaxPool2D(pool_size=3, strides=2)),
    ])


def _make_E():
    return _Branches([
        _conv(320, 1),
        _seq(_conv(384, 1),
             _Branches([_conv(384, (1, 3), padding=(0, 1)),
                        _conv(384, (3, 1), padding=(1, 0))])),
        _seq(_conv(448, 1), _conv(384, 3, padding=1),
             _Branches([_conv(384, (1, 3), padding=(0, 1)),
                        _conv(384, (3, 1), padding=(1, 0))])),
        _seq(nn.AvgPool2D(pool_size=3, strides=1, padding=1), _conv(192, 1)),
    ])


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_conv(32, 3, strides=2))
        self.features.add(_conv(32, 3))
        self.features.add(_conv(64, 3, padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_conv(80, 1))
        self.features.add(_conv(192, 3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "inceptionv3", kwargs.get("root"),
                        kwargs.get("ctx"))
    return Inception3(**kwargs)
