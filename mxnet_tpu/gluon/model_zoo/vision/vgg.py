"""VGG 11/13/16/19 (+BN variants).

Reference: python/mxnet/gluon/model_zoo/vision/vgg.py (class VGG, vgg_spec,
vgg11 … vgg19_bn, get_vgg).
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg"]


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        self.features = nn.HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(nn.Conv2D(filters[i], kernel_size=3,
                                            padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(strides=2))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(rate=0.5))
        self.features.add(nn.Dense(4096, activation="relu"))
        self.features.add(nn.Dropout(rate=0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "vgg%d%s" % (num_layers,
                                          "_bn" if kwargs.get("batch_norm")
                                          else ""), root, ctx)
    return net


def vgg11(**kwargs):
    return get_vgg(11, **kwargs)


def vgg13(**kwargs):
    return get_vgg(13, **kwargs)


def vgg16(**kwargs):
    return get_vgg(16, **kwargs)


def vgg19(**kwargs):
    return get_vgg(19, **kwargs)


def vgg11_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(11, **kwargs)


def vgg13_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(13, **kwargs)


def vgg16_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(16, **kwargs)


def vgg19_bn(**kwargs):
    kwargs["batch_norm"] = True
    return get_vgg(19, **kwargs)
