"""SqueezeNet 1.0/1.1 (reference: python/mxnet/gluon/model_zoo/vision/
squeezenet.py — _make_fire, SqueezeNet, squeezenet1_0/1_1)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from .... import ndarray as nd

__all__ = ["SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


class _Fire(HybridBlock):
    def __init__(self, squeeze_channels, expand1x1_channels,
                 expand3x3_channels, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze_channels, kernel_size=1,
                                 activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1_channels, kernel_size=1,
                                   activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3_channels, kernel_size=3,
                                   padding=1, activation="relu")

    def forward(self, x):
        x = self.squeeze(x)
        return nd.concat(self.expand1x1(x), self.expand3x3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1"), \
            "Unsupported SqueezeNet version %s: 1.0 or 1.1 expected" % version
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, kernel_size=7, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, kernel_size=3, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(_Fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, kernel_size=1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(pretrained=False, root=None, ctx=None, **kwargs):
    net = SqueezeNet("1.0", **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "squeezenet1.0", root, ctx)
    return net


def squeezenet1_1(pretrained=False, root=None, ctx=None, **kwargs):
    net = SqueezeNet("1.1", **kwargs)
    if pretrained:
        from ..model_store import load_pretrained
        load_pretrained(net, "squeezenet1.1", root, ctx)
    return net
