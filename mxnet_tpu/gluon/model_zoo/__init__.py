"""Model zoo (reference: python/mxnet/gluon/model_zoo/)."""
from . import vision
from . import bert
from . import ssd
from .vision import get_model
from .bert import BERTModel, bert_12_768_12, bert_24_1024_16

__all__ = ["vision", "bert", "ssd", "get_model", "BERTModel", "bert_12_768_12",
           "bert_24_1024_16"]
