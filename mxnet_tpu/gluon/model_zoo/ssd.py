"""SSD: Single Shot MultiBox Detector (BASELINE config 4).

Reference: example/ssd/symbol/symbol_builder.py (get_symbol_train — the
VGG16-reduced SSD-300), python/mxnet/... MultiBox ops
(src/operator/contrib/multibox_prior.cc / multibox_target.cc /
multibox_detection.cc), GluonCV's model_zoo.ssd for the gluon-style
composition.

TPU-first notes: every head is a 3x3 conv (MXU-friendly); anchors are
generated per feature map by the MultiBoxPrior op at trace time (static
shapes ⇒ one XLA program); training targets come from the MultiBoxTarget
op so the whole step stays jittable; inference decodes + NMS via
MultiBoxDetection.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from ... import ndarray as F
from ...ndarray.ndarray import invoke
from .. import nn
from ..block import HybridBlock
from ..loss import Loss

__all__ = ["SSD", "SSDMultiBoxLoss", "ssd_300_vgg16_voc", "ssd_toy"]


def _conv_block(channels, num_convs, pool=True):
    blk = nn.HybridSequential()
    for _ in range(num_convs):
        blk.add(nn.Conv2D(channels, 3, padding=1, activation="relu"))
    if pool:
        blk.add(nn.MaxPool2D(2, strides=2))
    return blk


def _down_block(channels, strides=2, padding=1):
    """1x1 bottleneck + 3x3 (the reference's extra layers; the last two
    SSD-300 extras use stride 1, pad 0 to reach 3x3 and 1x1 maps)."""
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels // 2, 1, activation="relu"),
            nn.Conv2D(channels, 3, strides=strides, padding=padding,
                      activation="relu"))
    return blk


class SSD(HybridBlock):
    """Multi-scale detector over a list of feature stages.

    forward(x) -> (anchors (1, A, 4), cls_preds (B, A, num_classes+1),
    box_preds (B, A*4)) — exactly the triple MultiBoxTarget/
    MultiBoxDetection consume."""

    def __init__(self, stages: Sequence[HybridBlock], num_classes: int,
                 sizes: Sequence[Tuple[float, float]],
                 ratios: Sequence[Sequence[float]], **kwargs):
        super().__init__(**kwargs)
        if not (len(stages) == len(sizes) == len(ratios)):
            raise ValueError("stages/sizes/ratios must align per scale")
        self.num_classes = num_classes
        self._sizes = [tuple(s) for s in sizes]
        self._ratios = [tuple(r) for r in ratios]
        self.stages = nn.HybridSequential()
        for s in stages:
            self.stages.add(s)
        self.class_predictors = nn.HybridSequential()
        self.box_predictors = nn.HybridSequential()
        for s, r in zip(self._sizes, self._ratios):
            a = len(s) + len(r) - 1          # anchors per position
            self.class_predictors.add(
                nn.Conv2D(a * (num_classes + 1), 3, padding=1))
            self.box_predictors.add(nn.Conv2D(a * 4, 3, padding=1))

    def forward(self, x):
        anchors, cls_preds, box_preds = [], [], []
        feat = x
        B = x.shape[0]
        for stage, cls_p, box_p, s, r in zip(
                self.stages, self.class_predictors, self.box_predictors,
                self._sizes, self._ratios):
            feat = stage(feat)
            anchors.append(invoke("MultiBoxPrior", feat, sizes=s, ratios=r,
                                  clip=False))
            # (B, aC, H, W) -> (B, H*W*a, C): channel-last flatten
            cp = cls_p(feat).transpose((0, 2, 3, 1)).reshape(
                (B, -1, self.num_classes + 1))
            bp = box_p(feat).transpose((0, 2, 3, 1)).reshape((B, -1))
            cls_preds.append(cp)
            box_preds.append(bp)
        anchors = F.concat(*anchors, dim=1) if len(anchors) > 1 \
            else anchors[0]
        cls_preds = F.concat(*cls_preds, dim=1) if len(cls_preds) > 1 \
            else cls_preds[0]
        box_preds = F.concat(*box_preds, dim=1) if len(box_preds) > 1 \
            else box_preds[0]
        return anchors, cls_preds, box_preds

    # -- training / inference glue -----------------------------------------
    def targets(self, anchors, cls_preds, labels,
                negative_mining_ratio=3.0):
        """MultiBoxTarget over this net's outputs (reference:
        training_targets in example/ssd)."""
        cls_preds_t = cls_preds.transpose((0, 2, 1))   # (B, C+1, A)
        return invoke("MultiBoxTarget", anchors, labels, cls_preds_t,
                      negative_mining_ratio=negative_mining_ratio)

    def detect(self, anchors, cls_preds, box_preds, nms_threshold=0.45,
               threshold=0.01, nms_topk=400):
        """Decode + NMS → (B, A, 6) [cls, score, x1, y1, x2, y2]."""
        cls_prob = invoke("softmax", cls_preds, axis=-1).transpose((0, 2, 1))
        return invoke("MultiBoxDetection", cls_prob, box_preds, anchors,
                      nms_threshold=nms_threshold, threshold=threshold,
                      nms_topk=nms_topk)


class SSDMultiBoxLoss(Loss):
    """Joint class + localization loss with hard-negative mining already
    applied by MultiBoxTarget (cls_target == -1 rows are ignored), matching
    the reference's MultiBoxLoss composition."""

    def __init__(self, rho=1.0, lambd=1.0, **kwargs):
        super().__init__(None, 0, **kwargs)
        self._rho = rho
        self._lambd = lambd

    def forward(self, cls_preds, box_preds, cls_target, loc_target,
                loc_mask):
        # cls: softmax CE over (B, A, C+1), ignoring -1 targets
        logp = invoke("log_softmax", cls_preds, axis=-1)
        valid = (cls_target >= 0)
        tgt = F.maximum(cls_target, F.zeros_like(cls_target))
        picked = invoke("pick", logp, tgt, axis=-1)
        n_valid = F.maximum(valid.astype("float32").sum(),
                           F.ones((1,)))
        cls_loss = -(picked * valid.astype("float32")).sum() / n_valid
        # loc: smooth-L1 on masked offsets
        diff = (box_preds - loc_target) * loc_mask
        loc_loss = invoke("smooth_l1", diff, scalar=self._rho).sum() / n_valid
        return cls_loss + self._lambd * loc_loss


def ssd_300_vgg16_voc(classes: int = 20, **kwargs) -> SSD:
    """SSD-300 with the VGG16(-style reduced) trunk (reference:
    example/ssd vgg16_reduced — conv4_3 + conv7 + 4 extra scales; 300x300
    input yields 38/19/10/5/3/1 feature maps)."""
    trunk = nn.HybridSequential()           # -> conv4_3 at 38x38
    trunk.add(_conv_block(64, 2), _conv_block(128, 2))
    c3 = nn.HybridSequential()              # pool3 is CEIL-mode: 75 -> 38
    for _ in range(3):
        c3.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
    c3.add(nn.MaxPool2D(2, strides=2, ceil_mode=True))
    trunk.add(c3)
    trunk.add(*[nn.Conv2D(512, 3, padding=1, activation="relu")
                for _ in range(3)])
    s2 = nn.HybridSequential()              # conv5 + fc6/fc7-as-conv at 19x19
    s2.add(nn.MaxPool2D(2, strides=2), _conv_block(512, 3, pool=False),
           nn.MaxPool2D(3, strides=1, padding=1),  # SSD's stride-1 pool5
           nn.Conv2D(1024, 3, padding=6, dilation=6, activation="relu"),
           nn.Conv2D(1024, 1, activation="relu"))
    stages: List[HybridBlock] = [
        trunk, s2,
        _down_block(512),                       # 19 -> 10
        _down_block(256),                       # 10 -> 5
        _down_block(256, strides=1, padding=0),  # 5 -> 3
        _down_block(256, strides=1, padding=0),  # 3 -> 1
    ]
    sizes = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961)]
    ratios = [(1, 2, 0.5)] + [(1, 2, 0.5, 3, 1.0 / 3)] * 3 \
        + [(1, 2, 0.5)] * 2
    return SSD(stages, classes, sizes, ratios, **kwargs)


def ssd_toy(classes: int = 2, **kwargs) -> SSD:
    """Tiny SSD for tests: 2 scales over a small conv trunk."""
    s1 = nn.HybridSequential()
    s1.add(_conv_block(16, 1), _conv_block(32, 1))
    s2 = _down_block(64)
    return SSD([s1, s2], classes,
               sizes=[(0.2, 0.3), (0.5, 0.6)],
               ratios=[(1, 2, 0.5)] * 2, **kwargs)
