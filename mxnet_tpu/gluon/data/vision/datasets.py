"""Vision datasets.

Reference: python/mxnet/gluon/data/vision/datasets.py (MNIST, FashionMNIST,
CIFAR10, CIFAR100, ImageFolderDataset, ImageRecordDataset).

The idx-gz (MNIST) and pickle (CIFAR) file formats are read natively.  This
environment has no network egress, so datasets resolve only from an existing
`root` directory; `SyntheticImageDataset` provides the deterministic stand-in
the convergence tests use (tests/train pattern, SURVEY.md §4.4).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Optional

import numpy as _np

from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset",
           "SyntheticImageDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx-ubyte(.gz) files (reference: datasets.MNIST)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    @staticmethod
    def _read_idx(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic = struct.unpack(">I", f.read(4))[0]
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return _np.frombuffer(f.read(), _np.uint8).reshape(dims)

    def _find(self, name):
        for cand in (name, name + ".gz"):
            p = os.path.join(self._root, cand)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(
            "MNIST file %s not found under %s (no network egress; place the "
            "idx files there or use SyntheticImageDataset for smoke tests)"
            % (name, self._root))

    def _get_data(self):
        img_name, lbl_name = self._train_files if self._train else \
            self._test_files
        images = self._read_idx(self._find(img_name))
        labels = self._read_idx(self._find(lbl_name))
        self._data = images[..., None]  # HWC, C=1
        self._label = labels.astype(_np.int32)


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches (reference: datasets.CIFAR10
    reads the binary .bin variant; both are supported here)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _load_pickles(self, names):
        data, labels = [], []
        for n in names:
            path = os.path.join(self._root, n)
            if not os.path.exists(path):
                alt = os.path.join(self._root, "cifar-10-batches-py", n)
                if os.path.exists(alt):
                    path = alt
                else:
                    raise FileNotFoundError(
                        "CIFAR batch %s not found under %s" % (n, self._root))
            with open(path, "rb") as f:
                batch = pickle.load(f, encoding="latin1")
            data.append(_np.asarray(batch["data"], _np.uint8))
            labels.extend(batch.get("labels", batch.get("fine_labels")))
        data = _np.concatenate(data).reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), _np.asarray(labels, _np.int32)

    def _get_data(self):
        names = ["data_batch_%d" % i for i in range(1, 6)] if self._train \
            else ["test_batch"]
        self._data, self._label = self._load_pickles(names)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=True, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        names = ["train"] if self._train else ["test"]
        self._data, self._label = self._load_pickles(names)


class ImageFolderDataset(Dataset):
    """A folder of class subfolders of images (reference:
    ImageFolderDataset).  Decoding goes through mx.image.imread."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".bmp", ".npy"]
        self.synsets = []
        self.items = []
        self._list_images(self._root)

    def _list_images(self, root):
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ....image import imread
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = _np.load(path)
        else:
            img = imread(path, self._flag).asnumpy()
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class ImageRecordDataset(Dataset):
    """Images in a RecordIO file (reference: ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from ....recordio import unpack_img
        record = self._record[idx]
        header, img = unpack_img(record)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class SyntheticImageDataset(Dataset):
    """Deterministic learnable dataset: per-class Gaussian prototypes.
    Stand-in for MNIST/ImageNet smoke+convergence tests in a no-egress
    environment (the reference nightly uses real data; SURVEY.md §4.4)."""

    def __init__(self, num_samples=1000, shape=(28, 28, 1), num_classes=10,
                 seed=42, noise=0.15, dtype="uint8", proto_seed=1234):
        # class prototypes come from proto_seed so train/test splits built
        # with different `seed`s share the same underlying classes
        protos = _np.random.RandomState(proto_seed).rand(
            num_classes, *shape).astype(_np.float32)
        rng = _np.random.RandomState(seed)
        labels = rng.randint(0, num_classes, num_samples).astype(_np.int32)
        imgs = protos[labels] + noise * rng.randn(num_samples, *shape) \
            .astype(_np.float32)
        imgs = _np.clip(imgs, 0, 1)
        if dtype == "uint8":
            self._data = (imgs * 255).astype(_np.uint8)
        else:
            self._data = imgs.astype(dtype)
        self._label = labels

    def __len__(self):
        return len(self._label)

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]
