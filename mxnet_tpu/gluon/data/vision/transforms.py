"""Vision transforms.

Reference: python/mxnet/gluon/data/vision/transforms.py (Compose, Cast,
ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop,
RandomFlipLeftRight, RandomFlipTopBottom, RandomBrightness, RandomContrast,
RandomSaturation, RandomLighting).

Transforms run host-side on HWC uint8/float NumPy or NDArray samples inside
the DataLoader workers (the reference's OpenCV augmenters); the batched
result makes one host→HBM transfer.
"""
from __future__ import annotations

import random as _pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from .... import ndarray as nd
from ....ndarray.ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomLighting", "RandomColorJitter", "CropResize", "RandomHue", "RandomGray", "Rotate", "RandomRotation"]


def _to_np(x) -> _np.ndarray:
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class Compose(Sequential):
    """Sequentially apply transforms (reference: transforms.Compose)."""

    def __init__(self, transforms: List[Block]):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        if isinstance(x, NDArray):
            return x.astype(self._dtype)
        return nd.array(_to_np(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: ToTensor)."""

    def forward(self, x):
        arr = _to_np(x).astype(_np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return nd.array(arr)


class Normalize(Block):
    """(x - mean) / std per channel on CHW input (reference: Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, _np.float32)
        self._std = _np.asarray(std, _np.float32)

    def forward(self, x):
        arr = _to_np(x).astype(_np.float32)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd.array((arr - mean) / std)


def _resize_np(img: _np.ndarray, size: Tuple[int, int]) -> _np.ndarray:
    """Bilinear resize HWC via separable linear interpolation (the role of
    OpenCV resize in src/io/image_aug_default.cc)."""
    h, w = img.shape[:2]
    out_w, out_h = size
    if (h, w) == (out_h, out_w):
        return img
    ys = _np.linspace(0, h - 1, out_h)
    xs = _np.linspace(0, w - 1, out_w)
    y0 = _np.floor(ys).astype(int)
    x0 = _np.floor(xs).astype(int)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img_f = img.astype(_np.float32)
    if img_f.ndim == 2:
        img_f = img_f[:, :, None]
    top = img_f[y0][:, x0] * (1 - wx) + img_f[y0][:, x1] * wx
    bot = img_f[y1][:, x0] * (1 - wx) + img_f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if img.dtype == _np.uint8:
        out = _np.clip(out, 0, 255).astype(_np.uint8)
    return out if img.ndim == 3 else out[:, :, 0]


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._keep = keep_ratio

    def forward(self, x):
        img = _to_np(x)
        w, h = self._size
        if self._keep:
            ih, iw = img.shape[:2]
            scale = min(w / iw, h / ih)
            w, h = int(iw * scale), int(ih * scale)
        return nd.array(_resize_np(img, (w, h)))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        img = _to_np(x)
        h, w = img.shape[:2]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        out = img[y0:y0 + ch, x0:x0 + cw]
        if out.shape[:2] != (ch, cw):
            out = _resize_np(out, (cw, ch))
        return nd.array(out)


class CropResize(Block):
    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._x, self._y, self._w, self._h = x, y, width, height
        self._size = size

    def forward(self, data):
        img = _to_np(data)
        out = img[self._y:self._y + self._h, self._x:self._x + self._w]
        if self._size:
            size = (self._size, self._size) if isinstance(self._size, int) \
                else tuple(self._size)
            out = _resize_np(out, size)
        return nd.array(out)


class RandomResizedCrop(Block):
    """Random area/aspect crop then resize (reference: RandomResizedCrop —
    the ImageNet training augmentation)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4., 4. / 3.),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        img = _to_np(x)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _pyrandom.uniform(*self._scale) * area
            aspect = _pyrandom.uniform(*self._ratio)
            cw = int(round((target_area * aspect) ** 0.5))
            ch = int(round((target_area / aspect) ** 0.5))
            if cw <= w and ch <= h:
                x0 = _pyrandom.randint(0, w - cw)
                y0 = _pyrandom.randint(0, h - ch)
                crop = img[y0:y0 + ch, x0:x0 + cw]
                return nd.array(_resize_np(crop, self._size))
        return nd.array(_resize_np(img, self._size))


class RandomFlipLeftRight(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() < self._p:
            return nd.array(_to_np(x)[:, ::-1].copy())
        return x if isinstance(x, NDArray) else nd.array(_to_np(x))


class RandomFlipTopBottom(Block):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() < self._p:
            return nd.array(_to_np(x)[::-1].copy())
        return x if isinstance(x, NDArray) else nd.array(_to_np(x))


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + _pyrandom.uniform(-self._b, self._b)
        return nd.array(_to_np(x).astype(_np.float32) * alpha)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self._c, self._c)
        gray = img.mean()
        return nd.array(gray + alpha * (img - gray))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self._s, self._s)
        if img.ndim == 3 and img.shape[2] == 3:
            gray = img @ _np.array([0.299, 0.587, 0.114], _np.float32)
            return nd.array(gray[:, :, None] + alpha * (img - gray[:, :, None]))
        return nd.array(img)


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference: RandomLighting)."""

    _eigval = _np.array([55.46, 4.794, 1.148], _np.float32)
    _eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        img = _to_np(x).astype(_np.float32)
        if img.ndim != 3 or img.shape[2] != 3:
            return nd.array(img)
        alpha = _np.random.normal(0, self._alpha, 3).astype(_np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd.array(img + rgb)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))

    def forward(self, x):
        ts = list(self._ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            x = t(x)
        return x


class RandomHue(Block):
    """Random hue jitter (reference: transforms.RandomHue over the
    _image_random_hue kernel)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        from ....ndarray.ndarray import invoke
        return invoke("_image_random_hue", nd.array(_to_np(x)),
                      min_factor=-self._h, max_factor=self._h)


class RandomGray(Block):
    """With probability p, collapse to ITU-R BT.601 luma replicated over
    channels (reference: transforms.RandomGray)."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if _pyrandom.random() >= self._p:
            return x if isinstance(x, nd.NDArray) else nd.array(_to_np(x))
        a = _to_np(x).astype(_np.float32)
        luma = (0.299 * a[..., 0] + 0.587 * a[..., 1]
                + 0.114 * a[..., 2])
        return nd.array(_np.stack([luma] * a.shape[-1], axis=-1))


class Rotate(Block):
    """Rotate by a FIXED angle (degrees, counter-clockwise), bilinear
    with zero padding (reference: transforms.Rotate)."""

    def __init__(self, rotation_degrees, zoom_in=False, zoom_out=False):
        super().__init__()
        if zoom_in or zoom_out:
            raise NotImplementedError(
                "Rotate: zoom_in/zoom_out not implemented")
        self._deg = rotation_degrees

    def forward(self, x):
        return _rotate_hwc(x, self._deg)


class RandomRotation(Block):
    """Rotate by a uniform random angle from [lo, hi] degrees
    (reference: transforms.RandomRotation)."""

    def __init__(self, angle_limits, zoom_in=False, zoom_out=False,
                 rotate_with_proba=1.0):
        super().__init__()
        if zoom_in or zoom_out:
            raise NotImplementedError(
                "RandomRotation: zoom_in/zoom_out not implemented")
        self._lim = angle_limits
        self._p = rotate_with_proba

    def forward(self, x):
        if _pyrandom.random() >= self._p:
            return x if isinstance(x, nd.NDArray) else nd.array(_to_np(x))
        deg = _pyrandom.uniform(*self._lim)
        return _rotate_hwc(x, deg)


def _rotate_hwc(x, deg):
    """HWC rotate about the center via the BilinearSampler kernel (the
    affine grid is the rotation matrix)."""
    import math
    from ....ndarray.ndarray import invoke
    a = _to_np(x).astype(_np.float32)
    chw = _np.moveaxis(a, -1, 0)[None]                # (1, C, H, W)
    # grid maps output→input, and the image y-axis points down: the
    # CCW array-coords rotation needs the NEGATED angle here (pinned
    # against np.rot90 in tests).  Normalized grid units differ per axis
    # for H != W — the sin terms carry the aspect ratio so the rotation
    # stays RIGID in pixel space.
    th = -math.radians(deg)
    H, W = a.shape[0], a.shape[1]
    sx = max(W - 1, 1) / 2.0
    sy = max(H - 1, 1) / 2.0
    theta = _np.array([[math.cos(th), math.sin(th) * sy / sx, 0.0,
                        -math.sin(th) * sx / sy, math.cos(th), 0.0]],
                      _np.float32)
    grid = invoke("GridGenerator", nd.array(theta),
                  transform_type="affine",
                  target_shape=(a.shape[0], a.shape[1]))
    out = invoke("BilinearSampler", nd.array(chw), grid)
    return nd.array(_np.moveaxis(out.asnumpy()[0], 0, -1))
