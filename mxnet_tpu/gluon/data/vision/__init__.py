"""Vision data (reference: python/mxnet/gluon/data/vision/)."""
from . import transforms
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,
                       ImageFolderDataset, ImageRecordDataset,
                       SyntheticImageDataset)

__all__ = ["transforms", "MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset", "ImageRecordDataset",
           "SyntheticImageDataset"]
