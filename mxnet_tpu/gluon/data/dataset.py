"""Datasets.

Reference: python/mxnet/gluon/data/dataset.py (Dataset, SimpleDataset,
ArrayDataset, RecordFileDataset, _LazyTransformDataset).
"""
from __future__ import annotations

from typing import Any, Callable, List

from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """Abstract dataset: __getitem__ + __len__ (reference: data.Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn: Callable) -> "Dataset":
        indices = [i for i in range(len(self)) if fn(self[i])]
        return _FilteredDataset(self, indices)

    def shard(self, num_shards: int, index: int) -> "Dataset":
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return _ShardedDataset(self, list(range(start, end)))

    def take(self, count: int) -> "Dataset":
        return _ShardedDataset(self, list(range(min(count, len(self)))))

    def sample(self, sampler) -> "Dataset":
        return _ShardedDataset(self, list(sampler))

    def transform(self, fn: Callable, lazy: bool = True) -> "Dataset":
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn: Callable, lazy: bool = True) -> "Dataset":
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _FilteredDataset(SimpleDataset):
    def __init__(self, dataset, indices):
        super().__init__(dataset)
        self._indices = indices

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._data[self._indices[idx]]


_ShardedDataset = _FilteredDataset


class _LazyTransformDataset(Dataset):
    def __init__(self, dataset, fn):
        self._data = dataset
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays (reference: data.ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0, "Needs at least 1 arrays"
        self._length = len(args[0])
        self._data: List[Any] = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                "All arrays must have the same length; array[0] has length " \
                "%d while array[%d] has %d." % (self._length, i, len(data))
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)


class RecordFileDataset(Dataset):
    """Dataset over an indexed RecordIO file (reference:
    data.RecordFileDataset over MXIndexedRecordIO)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        self._filename = filename
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
