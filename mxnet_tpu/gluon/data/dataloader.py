"""DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py (class DataLoader,
_MultiWorkerIter, worker_loop, default_batchify_fn,
default_mp_batchify_fn).

Worker model (matches the reference): ``num_workers > 0`` runs decode/
augment in a pool of *worker processes* — the only way Python-side
augmentation escapes the GIL at TPU-feeding rates (SURVEY §7.2 hard part
7: a v5e-8 needs ~3k decoded img/s).  ``thread_pool=True`` opts into the
lighter thread pool instead (enough when PIL's C codecs dominate).

TPU specifics of the process path:
  * workers use the ``spawn`` start method — forking a process that holds
    a live PJRT client is undefined behaviour, spawn never inherits one;
  * workers are pinned to the CPU backend (env + ``pin_cpu``) so they can
    never touch the TPU tunnel;
  * the dataset and batchify fn are shipped ONCE per worker via the pool
    initializer (reference: worker_loop gets the dataset at fork), not
    per batch;
  * workers return plain NumPy trees (reference: default_mp_batchify_fn);
    the parent assembles them into NDArrays, so each batch makes a single
    host→HBM transfer (pin_memory's role — PJRT owns staging buffers).
"""
from __future__ import annotations

import multiprocessing as _mp
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as _np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(list(data))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    out = _np.asarray(data)
    return nd.array(out)


def default_mp_batchify_fn(data):
    """Worker-side batchify: stack into NumPy (reference:
    default_mp_batchify_fn — workers must not build device arrays)."""
    if isinstance(data[0], NDArray):
        return _np.stack([d.asnumpy() for d in data])
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_mp_batchify_fn(list(i)) for i in data]
    return _np.asarray(data)


def _to_numpy_tree(batch):
    if isinstance(batch, NDArray):
        return batch.asnumpy()
    if isinstance(batch, tuple) and hasattr(batch, "_fields"):  # namedtuple
        return type(batch)(*(_to_numpy_tree(b) for b in batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(_to_numpy_tree(b) for b in batch)
    return batch


def _to_nd_tree(batch):
    if isinstance(batch, _np.ndarray):
        return nd.array(batch)
    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
        return type(batch)(*(_to_nd_tree(b) for b in batch))
    if isinstance(batch, (list, tuple)):
        return [_to_nd_tree(b) for b in batch]
    return batch


# -- worker-process globals (reference: worker_loop module state) -----------
_worker_dataset = None
_worker_batchify = None


_worker_init_error = None


def _worker_initializer(dataset_bytes, batchify_bytes):
    """Runs once in each spawned worker: pin the CPU backend, THEN
    unpickle the dataset/batchify.  The payloads travel as raw pickle
    bytes so no user object is unpickled before the pin — a pool-respawned
    replacement worker (after an OOM-kill) must also never initialize the
    TPU backend, and it spawns with whatever env the parent has then.

    An unpickle failure must NOT raise here: a raising initializer makes
    multiprocessing respawn dying workers forever and the user only ever
    sees a timeout.  Record the error; _worker_fn reports it per task."""
    import pickle
    global _worker_dataset, _worker_batchify, _worker_init_error
    os.environ["MX_FORCE_CPU"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        from ...base import pin_cpu
        pin_cpu()
    except Exception:
        pass
    try:
        _worker_dataset = pickle.loads(dataset_bytes)
        _worker_batchify = pickle.loads(batchify_bytes)
    except Exception as e:  # e.g. dataset class only importable in parent
        _worker_init_error = "%s: %s" % (type(e).__name__, e)


def _worker_fn(indices):
    if _worker_init_error is not None:
        raise RuntimeError(
            "DataLoader worker could not reconstruct the dataset in the "
            "spawned process (%s). The dataset/batchify must be importable "
            "from the worker — move classes out of __main__, or use "
            "thread_pool=True." % _worker_init_error)
    samples = [_worker_dataset[i] for i in indices]
    return _to_numpy_tree(_worker_batchify(samples))


class DataLoader:
    """Iterate a Dataset in mini-batches (reference: gluon.data.DataLoader)."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 pin_device_id: int = 0, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn
        self._mp_pool = None

    def __len__(self):
        return len(self._batch_sampler)

    def __del__(self):
        self._shutdown_pool()

    def _shutdown_pool(self):
        pool = getattr(self, "_mp_pool", None)
        if pool is not None:
            pool.terminate()
            pool.join()
            self._mp_pool = None

    def _get_mp_pool(self):
        """Persistent spawn pool, created lazily and reused across epochs
        (reference keeps its worker pool for the DataLoader's lifetime)."""
        if self._mp_pool is None:
            import pickle
            ctx = _mp.get_context("spawn")
            batchify = self._batchify_fn or default_mp_batchify_fn
            try:
                payload = (pickle.dumps(self._dataset),
                           pickle.dumps(batchify))
            except Exception as e:
                raise RuntimeError(
                    "DataLoader(num_workers=%d) could not spawn workers "
                    "(dataset/batchify must be picklable for the process "
                    "pool — use thread_pool=True for unpicklable ones): %s"
                    % (self._num_workers, e)) from e
            self._mp_pool = ctx.Pool(
                self._num_workers, initializer=_worker_initializer,
                initargs=payload)
        return self._mp_pool

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return (self._batchify_fn or default_batchify_fn)(samples)

    def _depth(self):
        """In-flight batches: explicit prefetch honored (min 1 — the
        push-one-pop-one floor), default 2x workers."""
        return max(1, self._prefetch)

    def _iter_threads(self):
        """Thread-pool path (thread_pool=True): decode in threads, PIL's C
        codecs release the GIL."""
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._depth()):
                    futures.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                fut = futures.pop(0)
                try:
                    futures.append(pool.submit(self._load_batch, next(it)))
                except StopIteration:
                    pass
                yield fut.result(timeout=self._timeout)

    def _iter_processes(self):
        """Process-pool path (reference: _MultiWorkerIter) — ordered
        prefetch pipeline over the persistent spawn pool."""
        pool = self._get_mp_pool()
        pending = []
        it = iter(self._batch_sampler)
        try:
            for _ in range(self._depth()):
                pending.append(pool.apply_async(_worker_fn,
                                                (list(next(it)),)))
        except StopIteration:
            pass
        while pending:
            res = pending.pop(0)
            try:
                pending.append(pool.apply_async(_worker_fn,
                                                (list(next(it)),)))
            except StopIteration:
                pass
            yield _to_nd_tree(res.get(timeout=self._timeout))

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
        elif self._thread_pool:
            yield from self._iter_threads()
        else:
            yield from self._iter_processes()
