"""DataLoader.

Reference: python/mxnet/gluon/data/dataloader.py (class DataLoader,
_MultiWorkerIter, default_batchify_fn, default_mp_batchify_fn).

TPU-native: worker parallelism uses a thread pool rather than the
reference's multiprocessing workers — the heavy lifting (decode/augment) is
NumPy/PIL releasing the GIL, and forked processes do not mix with a live
PJRT client.  Batches are assembled host-side as one contiguous NumPy array
and make a single host→HBM transfer per batch (pin_memory's role — PJRT owns
the staging buffers).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as _np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(list(data))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    out = _np.asarray(data)
    return nd.array(out)


class DataLoader:
    """Iterate a Dataset in mini-batches (reference: gluon.data.DataLoader)."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None,
                 num_workers: int = 0, pin_memory: bool = False,
                 pin_device_id: int = 0, prefetch: Optional[int] = None,
                 thread_pool: bool = False, timeout: int = 120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn

    def __len__(self):
        return len(self._batch_sampler)

    def _load_batch(self, indices):
        samples = [self._dataset[i] for i in indices]
        return self._batchify_fn(samples)

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        # threaded prefetch pipeline (reference: _MultiWorkerIter)
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch or self._num_workers):
                    futures.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                fut = futures.pop(0)
                try:
                    futures.append(pool.submit(self._load_batch, next(it)))
                except StopIteration:
                    pass
                yield fut.result(timeout=self._timeout)
