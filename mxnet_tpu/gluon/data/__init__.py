"""Gluon data API (reference: python/mxnet/gluon/data/)."""
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .sampler import (Sampler, SequentialSampler, RandomSampler, BatchSampler,
                      FilterSampler, IntervalSampler)
from .dataloader import (DataLoader, default_batchify_fn,
                         default_mp_batchify_fn)
from . import vision

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset",
           "Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "FilterSampler", "IntervalSampler", "DataLoader",
           "default_batchify_fn", "default_mp_batchify_fn", "vision"]
