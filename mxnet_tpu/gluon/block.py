"""Gluon Block / HybridBlock / CachedOp.

Reference: python/mxnet/gluon/block.py (class Block — child/param
registration via __setattr__, collect_params, save_parameters /
load_parameters with structural names; class HybridBlock — hybridize,
_build_cache, _call_cached_op) and src/imperative/cached_op.cc
(CachedOp::Forward, OptimizeGraph, static_alloc).

TPU-native design (SURVEY.md §3.4 TPU mapping): ``hybridize()`` IS
``jax.jit``.  On call, the block's Python ``forward`` is traced once per
(input avals, param avals, mode) into a pure function of
(trainable-params, frozen-params, rng, inputs); jax.jit caches the compiled
XLA executable — the reference's CachedOp graph-optimization + static memory
planning are XLA's problem now.  Training uses the split-executable pattern:
one jitted forward that *returns its vjp* (a jax.tree_util.Partial whose
residuals stay in HBM) + one jitted backward applying it, so the steady-state
train step is exactly two XLA dispatches and the autograd tape records a
single fused node (SURVEY.md §7.2 item 1).
"""
from __future__ import annotations

import functools
import re
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..device import Context, current_context, cpu
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import NDArray
from .. import autograd
from .. import initializer as init_mod
from ..ops import random as _ops_random
from .parameter import (Parameter, Constant, ParameterDict,
                        DeferredInitializationError, _ParamOverrideScope)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class Block:
    """Base building block (reference: gluon.Block).

    Children and parameters are registered automatically on attribute
    assignment.  ``collect_params`` walks the tree producing structural
    names ("encoder.0.weight"), the 2.x naming scheme used by
    save_parameters/load_parameters.
    """

    def __init__(self, prefix: Optional[str] = None, params=None):
        # Use object.__setattr__: these must exist before __setattr__ logic.
        object.__setattr__(self, "_children", OrderedDict())
        object.__setattr__(self, "_reg_params", OrderedDict())
        object.__setattr__(self, "_forward_hooks", OrderedDict())
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        self._prefix = prefix or ""
        # v1.x compat: self.params.get('weight', shape=...) creates params
        self._params = ParameterDict(self._prefix, shared=params)
        self._scope_counter = 0

    # -- registration ------------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        object.__setattr__(self, name, value)

    def __delattr__(self, name: str) -> None:
        self._children.pop(name, None)
        self._reg_params.pop(name, None)
        object.__delattr__(self, name)

    def register_child(self, block: "Block", name: Optional[str] = None) -> None:
        if name is None:
            name = str(len(self._children))
        self._children[name] = block
        object.__setattr__(self, "_child_" + name, block)

    def register_forward_hook(self, hook: Callable) -> "_HookHandle":
        return _HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook: Callable) -> "_HookHandle":
        return _HookHandle(self._forward_pre_hooks, hook)

    # -- params ------------------------------------------------------------
    @property
    def params(self) -> ParameterDict:
        """Own (directly registered) parameters (v1.x surface)."""
        for n, p in self._reg_params.items():
            key = self._params.prefix + n
            if key not in self._params:
                self._params[key] = p
        return self._params

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        """All parameters in this tree, keyed by structural name."""
        out = ParameterDict(self._prefix)
        pattern = re.compile(select) if select else None
        for name, param in self._iter_params():
            if pattern and not pattern.search(name):
                continue
            param._structural_name = name
            out[name] = param
        return out

    def _iter_params(self, prefix: str = ""):
        for name, param in self._reg_params.items():
            yield (prefix + name if not prefix else prefix + name), param
        for cname, child in self._children.items():
            yield from child._iter_params(prefix + cname + ".")

    def sharding_spec(self, layout):
        """Per-parameter PartitionSpec overrides for sharded training
        (the SpecLayout hook, ISSUE 14).  Called by
        :meth:`mxnet_tpu.parallel.SpecLayout.resolve` on every block in
        the tree; return ``{param-attr-name-or-Parameter:
        jax.sharding.PartitionSpec}`` to pin a layout for this block's
        OWN parameters (``self._reg_params`` names, e.g. ``"weight"``),
        or an empty mapping to accept the layout's defaults (embeddings
        and linears split on ``tp``, everything else sheet-sharded on
        ``fsdp``).  A ``PartitionSpec()`` value forces replication;
        entries naming axes the mesh lacks (or that do not divide the
        dimension) degrade to replication rather than erroring, so one
        declaration serves every mesh class."""
        return {}

    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False) -> None:
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    def cast(self, dtype) -> None:
        for child in self._children.values():
            child.cast(dtype)
        for param in self._reg_params.values():
            param.cast(dtype)

    def apply(self, fn: Callable) -> "Block":
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def setattr(self, name, value):
        for _, param in self._iter_params():
            setattr(param, name, value)

    def share_parameters(self, shared: Dict[str, Parameter]) -> "Block":
        """2.x API: graft `shared` params into matching structural slots."""
        if isinstance(shared, ParameterDict):
            shared = dict(shared.items())
        structural = {name: (holder, attr)
                      for name, holder, attr in self._iter_param_slots()}
        for name, param in shared.items():
            if name in structural:
                holder, attr = structural[name]
                holder._reg_params[attr] = param
                object.__setattr__(holder, attr, param)
        return self

    def _iter_param_slots(self, prefix: str = ""):
        for attr in list(self._reg_params):
            yield prefix + attr, self, attr
        for cname, child in self._children.items():
            yield from child._iter_param_slots(prefix + cname + ".")

    # -- save / load -------------------------------------------------------
    def save_parameters(self, filename: str, deduplicate: bool = False) -> None:
        """Reference: Block.save_parameters — structural names, NDArray
        dict file format (readable by mx.nd.load)."""
        params = self.collect_params()
        arg_dict = {}
        seen = {}
        for name, param in params.items():
            if deduplicate and id(param) in seen:
                continue
            seen[id(param)] = name
            arg_dict[name] = param._reduce()
        _nd_mod.save(filename, arg_dict)

    def load_parameters(self, filename: str, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current") -> None:
        loaded = _nd_mod.load(filename)
        params = self.collect_params()
        loaded = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                  for k, v in loaded.items()}
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise AssertionError(
                        "Parameter %s is missing in %s. Set allow_missing=True "
                        "to ignore missing parameters" % (name, filename))
        for name, value in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise AssertionError(
                        "Parameter %s loaded from %s is not present in the "
                        "Block. Set ignore_extra=True to ignore" % (name, filename))
                continue
            param = params[name]
            if cast_dtype:
                if dtype_source == "saved":
                    param.cast(value.dtype)
                else:
                    value = value.astype(param.dtype)
            if param._data is None and param._deferred_init is None:
                param.initialize(ctx=ctx or cpu())
            param.set_data(value)

    save_params = save_parameters     # deprecated v1.x aliases
    load_params = load_parameters

    # -- call / forward ----------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        # remember input avals so export() can re-trace without a sample
        # (reference: CachedOp keeps the traced graph; here the trace is
        # reconstructed on demand from shapes)
        if args and all(isinstance(a, NDArray) for a in args):
            object.__setattr__(self, "_last_input_avals",
                               [(a.shape, str(a.dtype)) for a in args])
        out = self._call_impl(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def _call_impl(self, *args, **kwargs):
        try:
            return self._forward_maybe_v1(*args, **kwargs)
        except DeferredInitializationError:
            self._deferred_init_from(args)
            return self._forward_maybe_v1(*args, **kwargs)

    def _deferred_init_from(self, args) -> None:
        """Finish deferred param init using input shapes (reference:
        HybridBlock._deferred_infer_shape → Parameter._finish_deferred_init)."""
        self.infer_shape(*args)
        for param in self._reg_params.values():
            if param._deferred_init is not None:
                param._finish_deferred_init()

    def infer_shape(self, *args) -> None:
        """Leaf layers with deferred-shape params override this."""
        raise DeferredInitializationError(
            "%s has parameters with unknown shape and does not implement "
            "infer_shape" % type(self).__name__)

    def _forward_maybe_v1(self, *args, **kwargs):
        """Dispatch to forward(); v1.x-era subclasses may define
        hybrid_forward(F, x, **params) instead — inject F=nd + own params."""
        if type(self).forward not in Block._FORWARD_PLACEHOLDERS:
            return self.forward(*args, **kwargs)
        if hasattr(self, "hybrid_forward"):
            ctx = _first_ctx(args) or current_context()
            pkw = {n: p.data(ctx) for n, p in self._reg_params.items()}
            return self.hybrid_forward(_nd_mod, *args, **pkw, **kwargs)
        raise NotImplementedError(
            "%s must implement forward (or hybrid_forward)" % type(self).__name__)

    # set after HybridBlock is defined: {Block.forward, HybridBlock.forward}
    _FORWARD_PLACEHOLDERS: tuple = ()

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active: bool = True, **kwargs) -> None:
        """Recursively hybridize children (no-op on plain Blocks)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-block summary table (reference: Block.summary)."""
        rows = []

        def walk(block, name, depth):
            n_params = sum(int(_np.prod(p.shape)) if p.shape else 0
                           for p in block._reg_params.values())
            rows.append(("  " * depth + (name or type(block).__name__),
                         type(block).__name__, n_params))
            for cname, child in block._children.items():
                walk(child, cname, depth + 1)

        walk(self, type(self).__name__, 0)
        total = sum(r[2] for r in rows)
        lines = ["%-40s %-20s %12s" % ("Layer", "Type", "Params"),
                 "-" * 74]
        lines += ["%-40s %-20s %12d" % r for r in rows]
        lines += ["-" * 74, "Total params: %d" % total]
        print("\n".join(lines))

    def __repr__(self):
        body = "\n".join("  (%s): %s" % (k, repr(v).replace("\n", "\n  "))
                         for k, v in self._children.items())
        return "%s(\n%s\n)" % (type(self).__name__, body) if body else \
            "%s()" % type(self).__name__


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks: OrderedDict, hook: Callable):
        self._hooks = hooks
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        hooks[self._id] = hook

    def detach(self):
        self._hooks.pop(self._id, None)


def _first_ctx(args) -> Optional[Context]:
    for a in args:
        if isinstance(a, NDArray):
            return a.context
        if isinstance(a, (list, tuple)):
            c = _first_ctx(a)
            if c is not None:
                return c
    return None


def _flatten_nds(obj, out: List[NDArray]):
    """Collect NDArray leaves; return a template for rebuilding."""
    if isinstance(obj, NDArray):
        out.append(obj)
        return _LEAF
    if isinstance(obj, (list, tuple)):
        return type(obj)(_flatten_nds(x, out) for x in obj)
    return obj


_LEAF = object()


def _rebuild(template, leaves: List[Any], pos: List[int]):
    if template is _LEAF:
        v = leaves[pos[0]]
        pos[0] += 1
        return v
    if isinstance(template, (list, tuple)):
        return type(template)(_rebuild(t, leaves, pos) for t in template)
    return template


# Single process-wide backward executor: applies a vjp Partial to cotangents.
# The Partial's static structure is fixed per forward-trace, so this jit hits
# its cache every step (one XLA executable per cached graph).  Light-mode
# census (ISSUE 10): keeps jax.jit's C++ dispatch on the per-backward hot
# path while the program registry counts its (re)traces.
def _apply_vjp_body(vjp_fn, cotangents):
    return vjp_fn(cotangents)


def _make_apply_vjp():
    from ..programs import register_program
    return register_program("hybrid.apply_vjp", _apply_vjp_body,
                            mode="light", specializing=True)


_apply_vjp = _make_apply_vjp()


# Hybrid imperative-pass scope (ISSUE 13 retrace chase): while a
# hybridized ANCESTOR runs its imperative fallback pass (deferred
# params — the reference's _build_cache infer pass), nested hybridized
# children must run imperatively too.  Without this, the first resnet18
# step built 30 per-child programs plus 31 per-child backward (vjp)
# programs — ~2.7s of trace+compile and 60+ census "retraces" — all
# dead weight the moment the SECOND step traces the whole net as one
# program (children inline into an enclosing trace via the override
# scope; this scope closes the same hole for the imperative pass).
_imperative_pass = threading.local()


def _in_imperative_pass() -> bool:
    return getattr(_imperative_pass, "depth", 0) > 0


class _ImperativePassScope:
    __slots__ = ()

    def __enter__(self):
        _imperative_pass.depth = getattr(_imperative_pass, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _imperative_pass.depth -= 1
        return False


class _CacheEntry:
    """One compiled graph: key = (input avals, param avals, mode)."""
    __slots__ = ("fwd_infer", "fwd_train", "mutated_ids", "out_template",
                 "n_outs")

    def __init__(self):
        self.fwd_infer = None
        self.fwd_train = None
        self.mutated_ids: List[int] = []
        self.out_template = None
        self.n_outs = 0


class HybridBlock(Block):
    """Block that can be compiled into a cached XLA graph.

    Reference: gluon.HybridBlock (hybridize/_build_cache/_call_cached_op,
    export, optimize_for).  Steady state after hybridize():
      inference — one jitted executable;
      training  — fwd executable returning (outs, aux, vjp-Partial) + one
                  shared backward executable; the tape records one node.
    """

    def __init__(self, prefix: Optional[str] = None, params=None):
        super().__init__(prefix, params)
        object.__setattr__(self, "_active", False)
        object.__setattr__(self, "_cache", {})
        object.__setattr__(self, "_flags", {})
        object.__setattr__(self, "_monitor_all", False)

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, inline_limit: int = 2,
                  forward_bulk_size: Optional[int] = None,
                  backward_bulk_size: Optional[int] = None) -> None:
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape}
        self._cache = {}
        super().hybridize(active,
                          static_alloc=static_alloc, static_shape=static_shape)

    def _clear_cached_op(self):
        self._cache = {}

    # -- the cached-op path -------------------------------------------------
    def _call_impl(self, *args, **kwargs):
        from .parameter import _overrides
        from ..ndarray import ndarray as _ndmod
        # inside an enclosing trace, compose into it imperatively rather
        # than nesting a second jit (reference: CachedOp inlining); same
        # during SYMBOL tracing (export after hybridize+forward): nested
        # blocks must not run their jitted cache or tracers leak into the
        # symbol recorder
        if not self._active or _overrides() is not None \
                or _ndmod._sym_tracer is not None \
                or _in_imperative_pass():
            return super()._call_impl(*args, **kwargs)
        params = list(self.collect_params().items())
        # deferred params: first call runs imperatively (finishes deferred
        # init with real shapes — the reference's _build_cache infer pass).
        # The scope keeps hybridized CHILDREN imperative too: their
        # soon-obsolete per-child programs must not be built for a pass
        # the whole-net trace replaces on the next call.
        if any(p._data is None for _, p in params):
            with _ImperativePassScope():
                return super()._call_impl(*args, **kwargs)
        return self._call_cached(params, args, kwargs)

    def _call_cached(self, params, args, kwargs):
        in_leaves: List[NDArray] = []
        template = _flatten_nds(args, in_leaves)
        in_vals = [x._jax for x in in_leaves]
        ctx = _first_ctx(args) or current_context()

        trainable, frozen = [], []
        for _, p in params:
            (trainable if p.grad_req != "null" else frozen).append(p)
        recording = autograd.is_recording()
        training = autograd.is_training()
        backend = getattr(self, "_backend", None)
        from ..subgraph import get_backend as _get_backend
        from ..ops import attention as _att
        key = (tuple((v.shape, str(v.dtype)) for v in in_vals),
               tuple((p.shape, str(p.dtype)) for _, p in params),
               tuple(sorted(kwargs.items())) if kwargs else (),
               recording, training,
               # lowering identity: the property's cache token AND the
               # process-wide attention default the scoped impl falls back
               # to — changing either must retrace, never reuse a stale
               # executable
               _get_backend(backend).cache_token() if backend else None,
               _att._FORCED_IMPL)
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build_cache(params, trainable, frozen, template,
                                      len(in_vals), kwargs, recording, training)
            self._cache[key] = entry

        t_vals = tuple(p.data(ctx)._jax for p in trainable)
        f_vals = tuple(p.data(ctx)._jax for p in frozen)
        rng = _ops_random.next_key()

        # per-block lowering overrides active for trace AND execution:
        # jax.jit traces lazily on the first call of the jitted fn, so the
        # property scope must wrap the call, not just entry construction
        with self._backend_scope():
            if recording:
                outs, vjp_fn, mutated = entry.fwd_train(t_vals, f_vals, rng,
                                                        tuple(in_vals))
            else:
                outs, mutated = entry.fwd_infer(t_vals, f_vals, rng,
                                                tuple(in_vals))
                vjp_fn = None

        # write mutated aux state (BatchNorm running stats) back into params
        by_id = {id(p): p for _, p in params}
        for pid, new_val in zip(entry.mutated_ids, mutated):
            p = by_id[pid]
            arr = p.data(ctx)
            arr._set_jax(new_val.astype(arr.dtype))

        if vjp_fn is not None:
            def tape_vjp(cotangents):
                g_train, g_ins = _apply_vjp(vjp_fn, cotangents)
                return tuple(g_train) + tuple(g_ins)

            nd_inputs = [p.data(ctx) for p in trainable] + in_leaves
            wrapped = autograd.record_custom(
                tape_vjp, nd_inputs, tuple(outs), ctx,
                name=type(self).__name__)
        else:
            wrapped = [NDArray(o, ctx=ctx) for o in outs]
        return _rebuild(entry.out_template, wrapped, [0])

    def _build_cache(self, params, trainable, frozen, template, n_in,
                     kwargs, recording, training) -> _CacheEntry:
        """Trace forward into a pure jax function and jit it (reference:
        CachedOp::CachedOp + OptimizeGraph — here XLA does the optimizing)."""
        entry = _CacheEntry()
        block = self

        def run(t_vals, f_vals, rng, in_vals):
            # fresh tracer-backed NDArray per param; layers read them through
            # Parameter.data() via the override scope
            entry.mutated_ids = []
            overrides: Dict[int, NDArray] = {}
            tr_nds, fr_nds = [], []
            for p, v in zip(trainable, t_vals):
                nd = NDArray(v, ctx=cpu())
                overrides[id(p)] = nd
                tr_nds.append((p, nd))
            for p, v in zip(frozen, f_vals):
                nd = NDArray(v, ctx=cpu())
                overrides[id(p)] = nd
                fr_nds.append((p, nd))
            in_nds = [NDArray(v, ctx=cpu()) for v in in_vals]
            rebuilt = _rebuild(template, in_nds, [0])
            with _ParamOverrideScope(overrides), \
                    _ops_random.trace_key_scope(rng), \
                    autograd._Scope(False, training):
                out = Block._call_impl(block, *rebuilt, **kwargs)
            out_leaves: List[NDArray] = []
            entry.out_template = _flatten_nds(out, out_leaves)
            entry.n_outs = len(out_leaves)
            # detect aux-state mutation (chunk version bumped during trace)
            mutated_vals = []
            for p, nd in tr_nds + fr_nds:
                if nd._chunk.version > 0:
                    entry.mutated_ids.append(id(p))
                    mutated_vals.append(nd._jax)
            return tuple(o._jax for o in out_leaves), tuple(mutated_vals)

        from ..programs import register_program
        pname = "hybrid.%s" % type(self).__name__
        if recording:
            def fwd_train(t_vals, f_vals, rng, in_vals):
                def f(tv, iv):
                    return run(tv, f_vals, rng, iv)
                outs, vjp_fn, mutated = jax.vjp(f, t_vals, in_vals,
                                                has_aux=True)
                return outs, vjp_fn, mutated

            entry.fwd_train = register_program(pname + ".train",
                                               fwd_train, mode="light",
                                               specializing=True)
        else:
            entry.fwd_infer = register_program(pname + ".infer", run,
                                               mode="light",
                                               specializing=True)
        return entry

    # -- export (symbol.json + params artifact) -----------------------------
    def export(self, path: str, epoch: int = 0, remove_amp_cast: bool = True):
        """Serialize to `path-symbol.json` + `path-%04d.params` (reference:
        HybridBlock.export).  The JSON carries the block config; parameters
        use the MXNet binary dict format."""
        from ..symbol import symbol_json_from_block
        sym_file = "%s-symbol.json" % path
        with open(sym_file, "w") as f:
            f.write(symbol_json_from_block(self))
        params_file = "%s-%04d.params" % (path, epoch)
        arg_dict = {}
        for name, p in self.collect_params().items():
            arg_dict["arg:" + name] = p._reduce()
        _nd_mod.save(params_file, arg_dict)
        return sym_file, params_file

    def optimize_for(self, x, backend=None, clear=True, **kwargs):
        """Reference: HybridBlock.optimize_for(backend) — subgraph-backend
        selection via the backend-property registry (mxnet_tpu.subgraph;
        reference subgraph_property.h SubgraphPropertyRegistry).

        The named property's lowering overrides apply to THIS block only
        (per-block semantics like the reference, not process-wide): its
        scope is entered around every trace/execution of this block's
        cached op, and the cached-op key carries the backend name.
        Built-ins: ``'pallas'`` (force the Pallas flash-attention kernel
        where alignment permits), ``'xla'`` (plain jnp composition),
        ``'amp_bf16'`` / ``'amp_float16'`` (AMP policy lists scoped to the
        block).  ``None`` restores default lowering.  Unknown backends
        warn loudly instead of silently doing nothing."""
        from ..subgraph import get_backend
        if backend is None:
            self._backend = None
        else:
            try:
                get_backend(backend)
                self._backend = backend
            except KeyError as e:
                import warnings
                warnings.warn(
                    "optimize_for: %s; running the default XLA path" % e,
                    stacklevel=2)
                self._backend = None
        if clear:
            self._clear_cached_op()  # retrace under the new lowering config
        self.hybridize(True, **{k: v for k, v in kwargs.items()
                                if k in ("static_alloc", "static_shape")})
        return self(x)

    def _backend_scope(self):
        import contextlib
        backend = getattr(self, "_backend", None)
        if backend is None:
            return contextlib.nullcontext()
        from ..subgraph import get_backend
        return get_backend(backend).scope()

    def forward(self, *args, **kwargs):
        raise NotImplementedError


Block._FORWARD_PLACEHOLDERS = (Block.forward, HybridBlock.forward)


def functionalize(block: Block):
    """Lift a Block into (pure_fn, params) for direct jax use.

    ``pure_fn(param_values, *inputs)`` runs the block's forward with the
    given parameter arrays substituted (the CachedOp trace mechanism made
    public) — the bridge the parallel/ package uses to pjit whole training
    steps over a Mesh, and what __graft_entry__ exposes to the driver.
    Parameters must be initialized; keys are structural names.
    """
    params = list(block.collect_params().items())

    def pure_fn(param_values, *inputs, training=False):
        overrides: Dict[int, NDArray] = {}
        for name, p in params:
            overrides[id(p)] = NDArray(param_values[name], ctx=cpu())
        in_nds = [x if isinstance(x, NDArray) else NDArray(x, ctx=cpu())
                  for x in inputs]
        with _ParamOverrideScope(overrides), autograd._Scope(False, training):
            out = block(*in_nds)
        return jax.tree_util.tree_map(
            lambda o: o._jax if isinstance(o, NDArray) else o, out,
            is_leaf=lambda o: isinstance(o, NDArray))

    param_values = {name: p.data()._jax for name, p in params}
    return pure_fn, param_values


class SymbolBlock(HybridBlock):
    """Runs a network from exported symbol.json + params (reference:
    gluon.SymbolBlock.imports).  Full graph-json execution lands with the
    symbol subsystem; constructing from a live Symbol works now."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__()
        self._outputs = outputs
        self._inputs = inputs

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from ..symbol import load as sym_load
        sym = sym_load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        block = SymbolBlock(sym, input_names)
        if param_file:
            loaded = _nd_mod.load(param_file)
            if isinstance(loaded, list):
                if loaded:
                    raise MXNetError(
                        "SymbolBlock.imports: %r holds a name-less array "
                        "LIST; parameters need the dict form (arg:/aux: "
                        "keys)" % param_file)
                loaded = {}      # empty save is format-ambiguous
            block._sym_params = loaded
        else:
            block._sym_params = {}
        block._input_names = input_names
        return block

    def forward(self, *args):
        from ..symbol import evaluate as sym_eval
        feeds = dict(zip(self._input_names, args))
        params = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                  for k, v in self._sym_params.items()}
        return sym_eval(self._outputs, feeds, params)
