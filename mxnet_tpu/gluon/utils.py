"""Gluon utilities.

Reference: python/mxnet/gluon/utils.py (split_data, split_and_load,
clip_global_norm, check_sha1, download).
"""
from __future__ import annotations

import math
import os
from typing import List, Optional

import numpy as _np

from ..base import MXNetError
from ..device import Context
from ..ndarray.ndarray import NDArray
from .. import ndarray as nd

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True):
    """Split along batch_axis into num_slice chunks (reference:
    gluon.utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's a multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." % (
                str(data.shape), num_slice, batch_axis, num_slice))
    if num_slice == 1:
        return [data]
    if not even_split and size < num_slice:
        # fewer samples than slices: return `size` single-sample slices
        # (reference behavior — callers get fewer slices, never empty ones)
        num_slice = size
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list: List[Context], batch_axis: int = 0,
                   even_split: bool = True):
    """Split and move each slice to its context (reference:
    gluon.utils.split_and_load) — one host→HBM transfer per chip."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays: List[NDArray], max_norm: float,
                     check_isfinite: bool = True):
    """Rescale so the concatenated grad's L2 norm ≤ max_norm (reference:
    gluon.utils.clip_global_norm)."""
    if not arrays:
        raise ValueError("arrays must not be empty")

    def _norm(array):
        x = array.reshape(-1)
        return (x * x).sum()

    total = _norm(arrays[0])
    for arr in arrays[1:]:
        total = total + _norm(arr)
    total_norm = float(total.sqrt().asscalar())
    if check_isfinite and not math.isfinite(total_norm):
        import warnings
        warnings.warn(UserWarning("nan or inf is detected. Clipping results "
                                  "will be undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr *= scale
    return total_norm


def check_sha1(filename: str, sha1_hash: str) -> bool:
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url: str, path: Optional[str] = None, overwrite: bool = False,
             sha1_hash: Optional[str] = None, retries: int = 5,
             verify_ssl: bool = True) -> str:
    """Reference: gluon.utils.download.  This environment has no network
    egress; only already-downloaded files resolve."""
    fname = path if path and not os.path.isdir(path) else os.path.join(
        path or ".", url.split("/")[-1])
    if os.path.exists(fname) and not overwrite and (
            sha1_hash is None or check_sha1(fname, sha1_hash)):
        return fname
    raise MXNetError(
        "download(%s): no network egress in this environment and file %s "
        "is not present locally" % (url, fname))
