"""Gluon Trainer.

Reference: python/mxnet/gluon/trainer.py (class Trainer — _init_kvstore
decision, step = allreduce_grads + update, grad-clipping split, save/load
optimizer states).

TPU-native: with one device the step is pure fused-op updates; with several
contexts the gradient allreduce goes through kvstore ('device' default,
'ici' when accelerator contexts are present — the reference picks 'device'
vs 'nccl' the same way).  Pod-scale sharded training instead jits the whole
step over a Mesh (mxnet_tpu.parallel.TrainStep) but keeps this class's API.
"""
from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional

from ..base import MXNetError, get_env
from .. import optimizer as opt
from .. import telemetry as _telemetry
from ..kvstore import create as kv_create
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            param_list = [params[key] for key in sorted(list(params.keys()))]
        elif isinstance(params, (list, tuple)):
            param_list = list(params)
        else:
            raise ValueError(
                "First argument must be a list or dict of Parameters, got %s"
                % type(params))
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, param in enumerate(param_list):
            if not isinstance(param, Parameter):
                raise ValueError("First argument must contain Parameters, "
                                 "got %s" % type(param))
            self._param2idx[param.name] = i
            self._params.append(param)
            param._trainer = self
        # dense/sparse split, computed ONCE (grad_stype is fixed at
        # Parameter construction): the step hot loop must not re-derive
        # per-param storage types, and sparse grads take the per-param
        # row_sparse path while dense ones ride the fused/bucketed one
        self._sparse_indices = [i for i, p in enumerate(self._params)
                                if p._grad_stype == "row_sparse"]
        self._dense_indices = [i for i, p in enumerate(self._params)
                               if p._grad_stype != "row_sparse"]
        if compression_params is None:
            # ops knob: MX_GRAD_COMPRESS=int8|2bit|bf16 compresses the
            # gradient wire of any Trainer launched without explicit
            # compression_params (launch scripts flip it fleet-wide)
            default_compress = get_env("MX_GRAD_COMPRESS")
            if default_compress:
                compression_params = {"type": default_compress}
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init: List[Parameter] = []
        # _grad_hook callbacks fire from whatever thread runs backward
        # (incl. XLA host-callback threads); every handoff of the armed
        # overlap session goes through this lock so a hook never sees a
        # half-swapped session (ISSUE 5 overlap scheduling)
        self._hook_lock = threading.Lock()
        self._reset_kvstore()

    # pickling: the optimizer's param_dict reaches this Trainer through
    # Parameter._trainer, and save_states() pickles the optimizer — a
    # raw Lock cannot ride along, so drop it and re-create on load (a
    # fresh lock is correct: no hooks can be armed in a new process)
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_hook_lock"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._hook_lock = threading.Lock()

    # -- setup -------------------------------------------------------------
    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of " \
                "contexts, but Parameter %s is initialized on %s while " \
                "previous Parameters are initialized on %s" % (
                    param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _reset_kvstore(self):
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = [p for p in self._params]
        self._kv_broadcast_done: set = set()
        self._overlap = False
        self._exchange_session = None
        self._armed_set = None

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        try:
            import jax
            nproc = jax.process_count()
        except Exception:
            nproc = 1
        # multi-process (jax.distributed) needs the kvstore even with ONE
        # local context: the cross-process allreduce lives there
        if kvstore and (len(self._contexts) > 1 or nproc > 1):
            # pick 'ici' for accelerator contexts like the reference picks
            # nccl/device for GPUs
            if isinstance(kvstore, str):
                if kvstore == "device" and \
                        (nproc > 1 or any(c.canonical_type == "tpu"
                                          for c in self._contexts)):
                    kvstore = "ici"
                kv = kv_create(kvstore)
            else:
                kv = kvstore
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if update_on_kvstore is None:
                update_on_kvstore = False
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            self._update_on_kvstore = update_on_kvstore
            # overlap scheduling (ISSUE 5): exchange each fusion bucket as
            # soon as backward finalizes its last gradient, instead of
            # serializing the whole exchange behind backward.  Needs the
            # local-updater layout (the server-optimizer path must see the
            # full key set at once) and a store whose exchange dispatch is
            # async (begin_exchange returns None on the PS transport).
            self._overlap = not update_on_kvstore and \
                get_env("MX_EXCHANGE_OVERLAP", dtype=bool)
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    def _init_params(self):
        assert self._kv_initialized
        if self._kvstore is None:
            self._params_to_init = []
            return
        for i, param in enumerate(self._params):
            if param._deferred_init is not None \
                    or i in self._kv_broadcast_done:
                # already-broadcast params must NOT be re-pulled: after the
                # first step the store slot holds the reduced GRADIENT
                # (update_on_kvstore=False), not a weight
                continue
            # broadcast, not bare init: every device copy (and on multi-
            # process stores every WORKER) starts from the store's agreed
            # value — the reference Trainer._init_params kvstore.broadcast
            self._kvstore.broadcast(i, param.data(self._contexts[0]),
                                    out=param.list_data())
            self._kv_broadcast_done.add(i)
        self._params_to_init = [p for p in self._params_to_init
                                if p._deferred_init is not None]

    # -- properties --------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- whole-step compiled lane (ISSUE 7) --------------------------------
    def make_compiled_step(self, net, loss_fn, metric=None, layout=None):
        """A :class:`mxnet_tpu.step.CompiledStep` over this trainer:
        forward + loss + backward + this trainer's gradient exchange
        (incl. int8/2bit compression) + the fused optimizer apply (+ the
        metric's device accumulate) as ONE donated jit per step — the
        MX_STEP_COMPILE lane.  The returned object reads/writes this
        trainer's parameters, updater state and error-feedback residuals
        every dispatch, so eager ``step()`` calls, ``save_states`` and
        checkpoints interoperate; transports the trace cannot express
        (dist_async) fall back to the eager pipeline automatically.

        ``layout`` (a :class:`mxnet_tpu.parallel.SpecLayout`, or the
        MX_MESH_AXES/MX_FSDP env knobs when omitted) turns the step into
        the SHARDED one-donated-jit: parameters + optimizer state live
        FSDP/ZeRO-sheet- and TP-split across the layout's mesh, the
        batch splits over data×fsdp, gradients reduce-scatter onto the
        parameter shards (int8-quantized per bucket when this trainer
        carries compression_params) and XLA all-gathers updated
        parameters just in time — per-chip state bytes drop ~linearly
        with the fsdp axis (ISSUE 14)."""
        from ..step import CompiledStep
        return CompiledStep(net, loss_fn, self, metric=metric,
                            layout=layout)

    # -- the step ----------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce + update (reference: Trainer.step)."""
        rescale_grad = self._scale / batch_size
        self._check_and_rescale_grad(rescale_grad)
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        # flight recorder (ISSUE 8): one structured step record per
        # optimizer step — phase durations accumulated above + dispatch/
        # wire deltas; dispatch-time only, no host sync
        _telemetry.note_step(batch_size=batch_size)

    def allreduce_grads(self):
        """Separate allreduce for gradient manipulation between reduce and
        update (reference: Trainer.allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "allreduce_grads() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False."
        self._allreduce_grads()

    def _exchange_set(self):
        """(idxs, grad_lists) of params whose gradients need the exchange
        this step — the key set both the batched push/pull and the
        overlap session operate on."""
        idxs: List[int] = []
        grad_lists = []
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            grads = param.list_grad()
            if len(grads) <= 1 and not self._update_on_kvstore \
                    and self._kvstore.num_workers <= 1:
                # single grad, single worker: nothing to reduce — but a
                # multi-process store must still see the push (allreduce)
                continue
            idxs.append(i)
            grad_lists.append(grads)
        return idxs, grad_lists

    def _arm_exchange(self):
        """Open the NEXT step's overlap session and point each grad
        buffer's readiness hook at it: during the following backward,
        every finalized gradient notifies the session and a fusion
        bucket's exchange launches the moment its last member lands
        (reverse-parameter-order buckets, so late layers — produced first
        — go out first).  Results commit at drain (_allreduce_grads), so
        gradients read between backward and step() are untouched."""
        with self._hook_lock:
            self._exchange_session = None
        self._armed_set = None
        if not self._overlap or self._kvstore is None:
            return
        idxs, grad_lists = self._exchange_set()
        if not idxs:
            return
        sess = self._kvstore.begin_exchange(idxs, grad_lists)
        if sess is None:        # transport cannot overlap (dist_async)
            self._overlap = False
            return
        with self._hook_lock:
            self._exchange_session = sess
        self._armed_set = (idxs, grad_lists)
        for p, i in enumerate(idxs):
            for d, g in enumerate(grad_lists[p]):
                g._grad_hook = functools.partial(self._on_grad_ready, i, d)

    def _armed_set_current(self):
        """The armed session still covers exactly this step's exchange
        set: same param indices AND the same grad buffer objects (a
        grad_req flip or a force-reinit between steps changes either)."""
        if self._armed_set is None:
            return False
        idxs, grad_lists = self._exchange_set()
        a_idxs, a_lists = self._armed_set
        return idxs == a_idxs and \
            len(grad_lists) == len(a_lists) and \
            all(len(l) == len(al) and all(g is ag for g, ag in zip(l, al))
                for l, al in zip(grad_lists, a_lists))

    def _on_grad_ready(self, i, d):
        with self._hook_lock:
            sess = self._exchange_session
        if sess is not None:
            # notify OUTSIDE the lock: the session may launch a bucket
            # collective here, and the arm/drain paths must not wait on
            # that dispatch just to swap the session pointer
            sess.notify_key(i, d)

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        with self._hook_lock:
            sess = self._exchange_session
        if sess is not None and not self._armed_set_current():
            # the exchange set changed under the armed session (a param
            # frozen/unfrozen or re-initialized between steps): its plan
            # no longer covers this step — roll back any launched EF
            # state and fall through to a fresh session/serialized path
            sess.abort()
            sess = None
            with self._hook_lock:
                self._exchange_session = None
        if sess is None and self._overlap:
            # overlap enabled but no session was armed before this
            # backward (first step, or recovering from a fallback): run
            # THIS exchange through the session machinery too — drain
            # launches every pending unit — so the bucket layout (and the
            # error-feedback residual wire keys, which embed the bucket
            # CRC) is identical to the overlapped steps'
            idxs, grad_lists = self._exchange_set()
            if idxs:
                sess = self._kvstore.begin_exchange(idxs, grad_lists)
                if sess is None:    # transport cannot overlap (dist_async)
                    self._overlap = False
        if sess is not None:
            # overlap path: bucket exchanges already launched during
            # backward — launch stragglers and commit the results
            with self._hook_lock:
                self._exchange_session = None
            with _telemetry.phase("exchange"):
                sess.drain()
            self._arm_exchange()
            return
        idxs, grad_lists = self._exchange_set()
        if not idxs:
            self._arm_exchange()
            return
        # ONE batched push/pull for the whole key set: the store coalesces
        # small dense keys into fusion buckets (MX_KVSTORE_BUCKET_KB) so a
        # ResNet-scale model does a few bucket exchanges per step instead
        # of ~160 per-key ones
        with _telemetry.phase("exchange"):
            self._kvstore.push(idxs, grad_lists)
            if self._update_on_kvstore:
                # server-side optimizer ran on push: fetch updated weights
                self._kvstore.pull(
                    idxs, [self._params[i].list_data() for i in idxs])
            else:
                self._kvstore.pull(idxs, grad_lists)
        self._arm_exchange()

    def update(self, batch_size, ignore_stale_grad=False):
        """Separate update step (reference: Trainer.update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        assert not (self._kvstore and self._update_on_kvstore), \
            "update() when parameters are updated on kvstore " \
            "is not supported. Try setting `update_on_kvstore` to False."
        self._check_and_rescale_grad(self._scale / batch_size)
        self._update(ignore_stale_grad)

    def _check_and_rescale_grad(self, scale):
        if self._update_on_kvstore and self._kv_initialized and \
                self._optimizer.rescale_grad != scale:
            raise UserWarning(
                "Possible change in the `batch_size` from previous `step` "
                "detected. Optimizer gradient normalizing factor will not "
                "change w.r.t new batch_size when update_on_kvstore=True")
        self._optimizer.rescale_grad = scale
        for upd in self._updaters:
            upd.optimizer.rescale_grad = scale

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            return
        with _telemetry.phase("optimizer_apply"):
            for d, upd in enumerate(self._updaters):
                # dense params: ONE batched updater call per device — the
                # aggregate-enabled optimizer applies the whole group as a
                # single fused pytree dispatch
                idxs, gs, ws = [], [], []
                for i in self._dense_indices:
                    param = self._params[i]
                    if param.grad_req == "null":
                        continue
                    idxs.append(i)
                    ws.append(param.list_data()[d])
                    gs.append(param.list_grad()[d])
                if idxs:
                    upd(idxs, gs, ws)
                for i in self._sparse_indices:
                    param = self._params[i]
                    if param.grad_req == "null":
                        continue
                    # nnz discovery is a host sync (reference
                    # cast_storage); the update itself is a jitted
                    # gather/scatter — kept out of the fused dense group
                    grad = param.list_grad()[d].tostype("row_sparse")
                    upd(i, grad, param.list_data()[d])

    # -- states ------------------------------------------------------------
    def save_states(self, fname):
        """Pickled updater states incl. momentum buffers (reference:
        Trainer.save_states)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            assert not self._params_to_init, \
                "Cannot save trainer states when some parameters are not " \
                "yet initialized in kvstore."
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._params_to_init:
            self._init_params()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
        param_dict = {i: param for i, param in enumerate(self._params)}
        self._optimizer.param_dict = param_dict
