"""Basic neural-network layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (Dense, Dropout, BatchNorm,
LayerNorm, GroupNorm, InstanceNorm, Embedding, Flatten, Sequential,
HybridSequential, Lambda, HybridLambda, Identity) and activations.py.

All layers run the same code imperatively and under the hybridize trace
(ops dispatch through the registry; XLA fuses the norm/activation chains
into neighbouring matmuls — SURVEY.md §2.1 "Dense op kernels" row).
"""
from __future__ import annotations

from typing import Optional

import numpy as _np

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray.ndarray import NDArray, invoke
from ... import initializer as init
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm",
           "Embedding", "Flatten", "Lambda", "HybridLambda", "Identity",
           "Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "GELU", "Swish",
           "SiLU", "Concatenate", "HybridConcatenate"]


class Sequential(Block):
    """Stack of blocks (reference: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*layers[key])
            return net
        return layers[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Hybridizable stack (reference: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)()
            net.add(*layers[key])
            return net
        return layers[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer, weight layout (units, in_units) like the
    reference (src/operator/nn/fully_connected.cc row-major cuBLAS layout —
    here a jnp matmul the MXU tiles directly)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act = activation
        self.weight = Parameter("weight", shape=(units, in_units), dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter("bias", shape=(units,), dtype=dtype,
                                  init=init.create(bias_initializer)
                                  if isinstance(bias_initializer, str)
                                  else bias_initializer,
                                  allow_deferred_init=True)
        else:
            self.bias = None

    def infer_shape(self, x):
        in_units = int(_np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)
        if self.bias is not None:
            self.bias.shape = (self._units,)

    def forward(self, x):
        out = invoke("FullyConnected", x, self.weight.data(x.context),
                     None if self.bias is None else self.bias.data(x.context),
                     num_hidden=self._units, no_bias=self.bias is None,
                     flatten=self._flatten)
        if self._act:
            out = invoke("Activation", out, act_type=self._act)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return "Dense(%s -> %s, %s)" % (
            shape[1] if shape and len(shape) > 1 else None, self._units,
            self._act or "linear")


class Dropout(HybridBlock):
    """Reference: nn.Dropout — active only in train mode (autograd
    train_mode / is_training), scaled by 1/(1-p)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        from ... import autograd
        if not autograd.is_training() or self._rate == 0:
            return x
        return invoke("Dropout", x, p=self._rate, axes=tuple(self._axes),
                      mode="training")

    def __repr__(self):
        return "Dropout(p = %s, axes=%s)" % (self._rate, self._axes)


class BatchNorm(HybridBlock):
    """Reference: nn.BatchNorm over axis=1 (channels) with moving stats as
    aux states (running_mean/running_var mutated in train mode — the rebuild's
    aux_writeback path)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        ch = in_channels
        self.gamma = Parameter("gamma", shape=(ch,),
                               init=init.create(gamma_initializer),
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=(ch,),
                              init=init.create(beta_initializer),
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)
        self.running_mean = Parameter(
            "running_mean", shape=(ch,),
            init=init.create(running_mean_initializer), grad_req="null",
            allow_deferred_init=True)
        self.running_var = Parameter(
            "running_var", shape=(ch,),
            init=init.create(running_variance_initializer), grad_req="null",
            allow_deferred_init=True)

    def infer_shape(self, x):
        ch = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (ch,)

    def forward(self, x):
        from ... import autograd
        use_global = self._use_global_stats or not autograd.is_training()
        ctx = x.context
        return invoke("BatchNorm", x, self.gamma.data(ctx),
                      self.beta.data(ctx), self.running_mean.data(ctx),
                      self.running_var.data(ctx), eps=self._eps,
                      momentum=self._momentum, axis=self._axis,
                      fix_gamma=not self._scale,
                      use_global_stats=use_global)

    def __repr__(self):
        return "BatchNorm(axis=%s, eps=%s, momentum=%s, in_channels=%s)" % (
            self._axis, self._eps, self._momentum,
            self.gamma.shape[0] if self.gamma.shape else None)


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm (reference: contrib.nn.SyncBatchNorm).  On the
    mesh data path batch stats are reduced with psum inside the sharded step
    (parallel/); single-device semantics equal BatchNorm."""

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class LayerNorm(HybridBlock):
    """Reference: nn.LayerNorm (src/operator/nn/layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=init.create(gamma_initializer),
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=init.create(beta_initializer),
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)

    def infer_shape(self, x):
        ch = x.shape[self._axis]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        ctx = x.context
        return invoke("LayerNorm", x, self.gamma.data(ctx),
                      self.beta.data(ctx), axis=self._axis, eps=self._eps)


class GroupNorm(HybridBlock):
    """Reference: nn.GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._groups = num_groups
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,),
                               init=init.create(gamma_initializer),
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=(in_channels,),
                              init=init.create(beta_initializer),
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)

    def infer_shape(self, x):
        ch = x.shape[1]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        ctx = x.context
        return invoke("GroupNorm", x, self.gamma.data(ctx),
                      self.beta.data(ctx), num_groups=self._groups,
                      eps=self._eps)


class InstanceNorm(HybridBlock):
    """Reference: nn.InstanceNorm."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        self.gamma = Parameter("gamma", shape=(in_channels,), init=init.One(),
                               grad_req="write" if scale else "null",
                               allow_deferred_init=True)
        self.beta = Parameter("beta", shape=(in_channels,), init=init.Zero(),
                              grad_req="write" if center else "null",
                              allow_deferred_init=True)

    def infer_shape(self, x):
        ch = x.shape[1]
        self.gamma.shape = (ch,)
        self.beta.shape = (ch,)

    def forward(self, x):
        ctx = x.context
        return invoke("InstanceNorm", x, self.gamma.data(ctx),
                      self.beta.data(ctx), eps=self._eps)


class Embedding(HybridBlock):
    """Reference: nn.Embedding (src/operator/tensor/indexing_op.cc Embedding).
    Rowsparse gradient becomes a scatter-add on TPU (SURVEY.md sparse row)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        # sparse_grad: the trainer converts the (dense, mostly-zero-row)
        # tape gradient to row_sparse so the optimizer's lazy path touches
        # only rows the batch used (reference: Embedding sparse_grad)
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, init=weight_initializer,
                                grad_stype="row_sparse" if sparse_grad
                                else "default")

    def forward(self, x):
        return invoke("Embedding", x, self.weight.data(x.context),
                      input_dim=self._input_dim, output_dim=self._output_dim)

    def __repr__(self):
        return "Embedding(%s -> %s)" % (self._input_dim, self._output_dim)


class Flatten(HybridBlock):
    def forward(self, x):
        return invoke("flatten", x)

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    """Wrap a function as a Block (reference: nn.Lambda)."""

    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            self._func = getattr(nd, function)
            self._name = function
        else:
            self._func = function
            self._name = getattr(function, "__name__", "lambda")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return "Lambda(%s)" % self._name


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            self._func = getattr(nd, function)
            self._name = function
        else:
            self._func = function
            self._name = getattr(function, "__name__", "lambda")

    def forward(self, *args):
        return self._func(*args)

    def __repr__(self):
        return "HybridLambda(%s)" % self._name


class Concatenate(Sequential):
    """Run children on the same input, concat outputs (reference 2.x:
    nn.Concatenate)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self._axis)


class HybridConcatenate(HybridSequential):
    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis

    def forward(self, x):
        outs = [block(x) for block in self._children.values()]
        return nd.concat(*outs, dim=self._axis)


# ---------------------------------------------------------------------------
# activation layers (reference: gluon/nn/activations.py)
# ---------------------------------------------------------------------------


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act = activation

    def forward(self, x):
        return invoke("Activation", x, act_type=self._act)

    def __repr__(self):
        return "Activation(%s)" % self._act


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return invoke("LeakyReLU", x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    """Reference: nn.PReLU — learnable slope."""

    def __init__(self, alpha_initializer=init.Constant(0.25), in_channels=1,
                 **kwargs):
        super().__init__(**kwargs)
        self.alpha = Parameter("alpha", shape=(in_channels,),
                               init=alpha_initializer)

    def forward(self, x):
        return invoke("LeakyReLU", x, self.alpha.data(x.context),
                      act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return invoke("LeakyReLU", x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return invoke("LeakyReLU", x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def forward(self, x):
        return invoke("LeakyReLU", x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x):
        return x * invoke("sigmoid", x * self._beta)


SiLU = Swish
