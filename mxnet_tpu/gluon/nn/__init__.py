"""Gluon nn layers (reference: python/mxnet/gluon/nn/)."""
from ..block import Block, HybridBlock, SymbolBlock
from .basic_layers import *     # noqa: F401,F403
from .conv_layers import *      # noqa: F401,F403
from . import basic_layers, conv_layers

__all__ = (["Block", "HybridBlock", "SymbolBlock"] +
           basic_layers.__all__ + conv_layers.__all__)
