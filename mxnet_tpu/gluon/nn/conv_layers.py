"""Convolution and pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (_Conv, Conv1D-3D,
Conv1D-3DTranspose, _Pooling, MaxPool/AvgPool/GlobalMaxPool/GlobalAvgPool
1D-3D, ReflectionPad2D).

TPU-native: all convs lower to one `lax.conv_general_dilated` (MXU path);
pooling to `lax.reduce_window` (see ops/nn.py).  MXNet's NCHW/OIHW layouts
are kept at the API; XLA picks internal layouts for the MXU.
"""
from __future__ import annotations

from typing import Optional

import numpy as _np

from ...ndarray.ndarray import NDArray, invoke
from ... import initializer as init
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    """Shared conv implementation (reference: gluon nn _Conv)."""

    _op = "Convolution"

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 dtype="float32", **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size) if not isinstance(kernel_size, int) else 1
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = _tup(kernel_size, ndim)
        ndim = len(self._kernel)
        self._stride = _tup(strides, ndim)
        self._pad = _tup(padding, ndim)
        self._dilate = _tup(dilation, ndim)
        self._groups = groups
        self._layout = layout
        self._act = activation
        wshape = self._weight_shape(in_channels)
        self.weight = Parameter("weight", shape=wshape, dtype=dtype,
                                init=weight_initializer,
                                allow_deferred_init=True)
        if use_bias:
            self.bias = Parameter("bias", shape=(channels,), dtype=dtype,
                                  init=init.create(bias_initializer)
                                  if isinstance(bias_initializer, str)
                                  else bias_initializer,
                                  allow_deferred_init=True)
        else:
            self.bias = None

    def _weight_shape(self, in_channels):
        # OIHW: (num_filter, in_channels/groups, *kernel)
        return (self._channels, in_channels // self._groups if in_channels
                else 0) + self._kernel

    def infer_shape(self, x):
        in_channels = x.shape[1]
        self._in_channels = in_channels
        self.weight.shape = self._weight_shape(in_channels)
        if self.bias is not None:
            self.bias.shape = (self._channels,)

    def forward(self, x):
        ctx = x.context
        out = invoke(self._op, x, self.weight.data(ctx),
                     None if self.bias is None else self.bias.data(ctx),
                     kernel=self._kernel, stride=self._stride,
                     dilate=self._dilate, pad=self._pad,
                     num_filter=self._channels, num_group=self._groups,
                     no_bias=self.bias is None)
        if self._act:
            out = invoke("Activation", out, act_type=self._act)
        return out

    def __repr__(self):
        return "%s(%s -> %s, kernel_size=%s, stride=%s, padding=%s)" % (
            type(self).__name__, self._in_channels or None, self._channels,
            self._kernel, self._stride, self._pad)


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         dilation, groups, layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         dilation, groups, layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         dilation, groups, layout, **kwargs)


class _ConvTranspose(_Conv):
    _op = "Deconvolution"

    def __init__(self, channels, kernel_size, strides, padding, output_padding,
                 dilation, groups, layout, **kwargs):
        self._out_pad = None  # set after ndim known
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, **kwargs)
        self._out_pad = _tup(output_padding, len(self._kernel))

    def _weight_shape(self, in_channels):
        # Deconvolution weight layout: (in_channels, channels/groups, *kernel)
        return (in_channels if in_channels else 0,
                self._channels // self._groups) + self._kernel

    def forward(self, x):
        ctx = x.context
        out = invoke("Deconvolution", x, self.weight.data(ctx),
                     None if self.bias is None else self.bias.data(ctx),
                     kernel=self._kernel, stride=self._stride,
                     dilate=self._dilate, pad=self._pad,
                     adj=self._out_pad or (0,) * len(self._kernel),
                     num_filter=self._channels, num_group=self._groups,
                     no_bias=self.bias is None)
        if self._act:
            out = invoke("Activation", out, act_type=self._act)
        return out


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 **kwargs):
        super().__init__(channels, _tup(kernel_size, 1), strides, padding,
                         output_padding, dilation, groups, layout, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 2), strides, padding,
                         output_padding, dilation, groups, layout, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, _tup(kernel_size, 3), strides, padding,
                         output_padding, dilation, groups, layout, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout=None,
                 count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        self._kernel = pool_size
        self._stride = strides if strides is not None else pool_size
        self._pad = padding
        self._ceil = ceil_mode
        self._global = global_pool
        self._type = pool_type
        self._count_include_pad = count_include_pad

    def forward(self, x):
        return invoke("Pooling", x, kernel=self._kernel,
                      pool_type=self._type, global_pool=self._global,
                      stride=self._stride, pad=self._pad,
                      pooling_convention="full" if self._ceil else "valid",
                      count_include_pad=self._count_include_pad)

    def __repr__(self):
        return "%s(size=%s, stride=%s, padding=%s, ceil_mode=%s)" % (
            type(self).__name__, self._kernel, self._stride, self._pad,
            self._ceil)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 1), None if strides is None else
                         _tup(strides, 1), _tup(padding, 1), ceil_mode,
                         **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 2), None if strides is None else
                         _tup(strides, 2), _tup(padding, 2), ceil_mode,
                         **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tup(pool_size, 3), None if strides is None else
                         _tup(strides, 3), _tup(padding, 3), ceil_mode,
                         **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tup(pool_size, 1), None if strides is None else
                         _tup(strides, 1), _tup(padding, 1), ceil_mode,
                         pool_type="avg", count_include_pad=count_include_pad,
                         **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 2), None if strides is None else
                         _tup(strides, 2), _tup(padding, 2), ceil_mode,
                         pool_type="avg", count_include_pad=count_include_pad,
                         **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tup(pool_size, 3), None if strides is None else
                         _tup(strides, 3), _tup(padding, 3), ceil_mode,
                         pool_type="avg", count_include_pad=count_include_pad,
                         **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), global_pool=True, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), global_pool=True, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), global_pool=True,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), global_pool=True, pool_type="avg",
                         **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), global_pool=True,
                         pool_type="avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), global_pool=True,
                         pool_type="avg", **kwargs)


class ReflectionPad2D(HybridBlock):
    """Reference: nn.ReflectionPad2D (pad op with mode='reflect')."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = tuple(padding)

    def forward(self, x):
        return invoke("pad", x, mode="reflect", pad_width=self._padding)
