"""Gluon losses.

Reference: python/mxnet/gluon/loss.py (class Loss, L2Loss, L1Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss, CTCLoss,
HuberLoss, HingeLoss, SquaredHingeLoss, LogisticLoss, TripletLoss,
PoissonNLLLoss, CosineEmbeddingLoss).

Semantics preserved: `weight` scaling, per-example `sample_weight`
broadcasting via _apply_weighting, `batch_axis` mean reduction.
"""
from __future__ import annotations

import numpy as _np

from ..ndarray.ndarray import NDArray, invoke
from .. import ndarray as nd
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss", "SDMLLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    """Reference: gluon.loss._apply_weighting."""
    if sample_weight is not None:
        loss = invoke("broadcast_mul", loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (int, float)), "weight must be a number"
        loss = loss * weight
    return loss


def _batch_mean(loss, batch_axis):
    """Mean over all axes except batch (reference: F.mean(loss, axis=
    self._batch_axis, exclude=True))."""
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    if not axes:
        return loss
    return loss.mean(axis=axes)


class Loss(HybridBlock):
    """Base loss (reference: gluon.loss.Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (type(self).__name__,
                                            self._batch_axis, self._weight)


class L2Loss(Loss):
    """0.5 * (pred - label)^2 (reference scaling)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = (pred - label) ** 2
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = (pred - label).abs()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Reference: SigmoidBCELoss — numerically-stable log-sum-exp form when
    from_sigmoid=False, optional pos_weight."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = label.reshape(pred.shape)
        if not self._from_sigmoid:
            if pos_weight is None:
                # max(x,0) - x*z + log(1+exp(-|x|))
                loss = pred.relu() - pred * label + \
                    (1.0 + (-pred.abs()).exp()).log()
            else:
                log_weight = 1.0 + invoke("broadcast_mul", label,
                                          pos_weight - 1.0)
                loss = pred - pred * label + log_weight * \
                    ((1.0 + (-pred.abs()).exp()).log() + (-pred).relu())
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -((pred + eps).log() * label +
                         (1.0 - pred + eps).log() * (1.0 - label))
            else:
                loss = -(invoke("broadcast_mul", (pred + eps).log() * label,
                                pos_weight) +
                         (1.0 - pred + eps).log() * (1.0 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference: SoftmaxCELoss — sparse_label picks via one-hot/log_softmax;
    fused into the matmul's epilogue by XLA on TPU."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = pred.log_softmax(axis=self._axis)
        if self._sparse_label:
            loss = -invoke("pick", pred, label, axis=self._axis,
                           keepdims=False)
        else:
            label = label.reshape(pred.shape)
            loss = -(pred * label).sum(axis=self._axis, keepdims=False)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = pred.log_softmax(axis=self._axis)
        loss = label * ((label + 1e-12).log() - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference: gluon.loss.CTCLoss
    over src/operator/nn/ctc_loss.cc).  TPU-native: the alpha recursion runs
    as a lax.scan inside the `CTCLoss` op (ops/nn.py) — static shapes, no
    cuDNN CTC needed."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.transpose((1, 0, 2))  # -> TNC
        if self._label_layout == "TN":
            label = label.transpose((1, 0))
        loss = invoke("CTCLoss", pred, label,
                      None if pred_lengths is None else pred_lengths,
                      None if label_lengths is None else label_lengths)
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        err = (pred - label).abs()
        loss = nd.where((err > self._rho),
                        err - 0.5 * self._rho,
                        (0.5 / self._rho) * (err ** 2))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = (self._margin - pred * label).relu()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        loss = (self._margin - pred * label).relu() ** 2
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if label_format not in ("signed", "binary"):
            raise ValueError("label_format must be signed or binary")

    def forward(self, pred, label, sample_weight=None):
        label = label.reshape(pred.shape)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = pred.relu() - pred * label + (1.0 + (-pred.abs()).exp()).log()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return _batch_mean(loss, self._batch_axis)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = positive.reshape(pred.shape)
        negative = negative.reshape(pred.shape)
        axes = tuple(range(1, pred.ndim))
        loss = ((pred - positive) ** 2 - (pred - negative) ** 2).sum(axis=axes)
        loss = (loss + self._margin).relu()
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = target.reshape(pred.shape)
        if self._from_logits:
            loss = pred.exp() - target * pred
        else:
            loss = pred - target * (pred + epsilon).log()
        if self._compute_full:
            # Stirling approximation of log(target!)
            stirling = target * target.log() - target + \
                0.5 * (2 * _np.pi * target).log()
            stirling = nd.where(target <= 1, stirling * 0, stirling)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        input2 = input2.reshape(input1.shape)
        dot = (input1 * input2).sum(axis=-1)
        n1 = (input1 ** 2).sum(axis=-1).sqrt()
        n2 = (input2 ** 2).sum(axis=-1).sqrt()
        cos = dot / (n1 * n2 + 1e-12)
        label = label.reshape(cos.shape)
        loss = nd.where(label == 1, 1.0 - cos, (cos - self._margin).relu())
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference: gluon.loss.SDMLLoss):
    treats a (x1[i], x2[i]) batch as N retrieval problems — the pairwise
    distance matrix is turned into a distribution with softmax(-d) and
    pulled toward a label-smoothed identity via KL divergence."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = smoothing_parameter
        self._kl = KLDivLoss(from_logits=True)

    def forward(self, x1, x2):
        from .. import nd as _nd
        n = x1.shape[0]
        # squared euclidean distances between every (x1[i], x2[j]) pair
        x1sq = (x1 * x1).sum(axis=1).reshape((n, 1))
        x2sq = (x2 * x2).sum(axis=1).reshape((1, n))
        dist = x1sq + x2sq - 2.0 * _nd.dot(x1, x2.T)
        log_prob = _nd.log_softmax(-dist, axis=1)
        # label-smoothed identity target: diagonal keeps 1-s, the rest
        # shares s/(N-1)
        eye = _nd.eye(n)
        labels = eye * (1.0 - self._smoothing) + \
            (1.0 - eye) * (self._smoothing / max(n - 1, 1))
        return self._kl(log_prob, labels)
