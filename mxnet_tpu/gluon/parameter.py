"""Gluon Parameter / Constant / ParameterDict.

Reference: python/mxnet/gluon/parameter.py (class Parameter — deferred shape
init, grad_req, _check_and_get, load semantics; class Constant;
class ParameterDict [v1.x]).

TPU-native notes: a Parameter's storage is an NDArray whose chunk is a PJRT
HBM buffer.  Replication across contexts (the reference's per-GPU copies made
by Trainer/kvstore) keeps the same dict-of-ctx layout; the pod-scale data
path instead shards/replicates via `mxnet_tpu.parallel` meshes.  During a
hybridize trace (CachedOp), `data()` returns the tracer-backed override so
the same layer code runs imperative and traced (see block.py).
"""
from __future__ import annotations

import threading
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from ..device import Context, current_context, cpu
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import NDArray
from .. import initializer as init_mod

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its deferred shape was known."""


# Thread-local override map used while tracing a hybridized block: the trace
# substitutes tracer-backed NDArrays for parameter data (block.py CachedOp).
_trace_state = threading.local()


def _overrides() -> Optional[Dict[int, NDArray]]:
    return getattr(_trace_state, "param_overrides", None)


class _ParamOverrideScope:
    def __init__(self, mapping: Dict[int, NDArray]):
        self._mapping = mapping

    def __enter__(self):
        self._old = _overrides()
        _trace_state.param_overrides = self._mapping
        return self

    def __exit__(self, *exc):
        _trace_state.param_overrides = self._old
        return False


def _norm_dtype(dtype):
    """Normalize to np.dtype; bfloat16 kept as its ml_dtypes dtype."""
    if dtype is None:
        return _np.dtype("float32")
    if str(dtype) == "bfloat16":
        import jax.numpy as jnp
        return _np.dtype(jnp.bfloat16)
    return _np.dtype(dtype)


def _shape_complete(shape) -> bool:
    return shape is not None and all(
        d is not None and int(d) > 0 for d in shape)


def _param_census_arrays(p):
    """One parameter's live device buffers (data + grad, every ctx copy)
    for the buffer census."""
    out = []
    for store in (p._data, p._grad):
        if store:
            for nd in store.values():
                a = getattr(nd, "_jax", None)
                if a is not None:
                    out.append(a)
    return out


class Parameter:
    """A weight/bias/state of a Block (reference: gluon.Parameter).

    Supports deferred initialization: unknown dims are 0/None/-1 and get
    filled by the layer's first forward (Block.infer_shape path), matching
    Parameter._finish_deferred_init in the reference.
    """

    def __init__(self, name: Optional[str] = None, grad_req: str = "write",
                 shape=None, dtype="float32", lr_mult: float = 1.0,
                 wd_mult: float = 1.0, init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default"):
        self._name = name or ("param_" + uuid.uuid4().hex[:12])
        self._uuid = uuid.uuid4().hex
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = _norm_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        if stype not in ("default", "row_sparse", "csr"):
            raise ValueError("invalid stype %r" % stype)
        self._stype = stype
        self._grad_stype = grad_stype
        # ctx -> NDArray (reference keeps per-device copies)
        self._data: Optional["OrderedDict[Context, NDArray]"] = None
        self._grad: Optional["OrderedDict[Context, NDArray]"] = None
        self._deferred_init = None    # (init, ctx_list, default_init)
        self._structural_name = None  # set by Block registration walk
        # buffer-census attribution (ISSUE 10): every live param/grad
        # device buffer is claimed by the "params" owner bucket
        from .. import programs as _programs
        _programs.track_buffers("params", self, _param_census_arrays)

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> str:
        return self._structural_name or self._name

    @name.setter
    def name(self, value):
        self._name = value

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self._shape, self.dtype)

    # -- shape (settable while incomplete, like the reference) -------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(
            int(s1) in (0, -1) or s1 is None or int(s1) == int(s2)
            for s1, s2 in zip(self._shape, new_shape))
        if len(self._shape) != len(new_shape) or not unknown_ok:
            raise AssertionError(
                "Expected shape %s is incompatible with given shape %s for "
                "Parameter %s" % (str(new_shape), str(self._shape), self.name))
        self._shape = tuple(int(d) for d in new_shape)

    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str):
        if req not in ("write", "add", "null"):
            raise ValueError("grad_req must be write/add/null, got %r" % req)
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for arr in self._data.values():
                    arr._grad = None
                    arr._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    @property
    def stype(self):
        return self._stype

    # -- initialization ----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False):
        """Materialize data on ctx(s) (reference: Parameter.initialize)."""
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = self.init if self.init is not None else default_init
        if not _shape_complete(self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid shape "
                "%s and deferred init is not allowed" % (self.name, self._shape))
        self._init_impl(init, ctx)

    def _init_impl(self, init, ctx_list):
        host = _np.zeros(self._shape, dtype=_np.float32)
        holder = _nd_mod.array(host, ctx=cpu(),
                               dtype=_np.float32)
        init_fn = init_mod.create(init)
        init_fn(init_mod.InitDesc(self.name), holder)
        value = holder.asnumpy()
        self._data = OrderedDict()
        for c in ctx_list:
            self._data[c] = _nd_mod.array(value, ctx=c, dtype=self.dtype)
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._grad = OrderedDict()
        for c, arr in self._data.items():
            arr.attach_grad(self._grad_req)
            self._grad[c] = arr.grad

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter %s was not initialized" % self.name)
        if not _shape_complete(self._shape):
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s; run a forward pass or "
                "call infer_shape first" % (self.name, self._shape))
        init, ctx, default_init = self._deferred_init
        self._init_impl(init if init is not None else default_init, ctx)

    # -- access ------------------------------------------------------------
    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %s has not been initialized yet because its "
                    "shape is unknown; run a forward pass first" % self.name)
            raise RuntimeError(
                "Parameter %s has not been initialized. You should initialize "
                "parameters with Block.initialize() before use" % self.name)
        if ctx is list:   # sentinel: return all copies (reference idiom)
            return list(arr_dict.values())
        if ctx is None:
            if len(arr_dict) == 1:
                return next(iter(arr_dict.values()))
            ctx = current_context()
        if isinstance(ctx, Context) and ctx in arr_dict:
            return arr_dict[ctx]
        raise RuntimeError(
            "Parameter %s was not initialized on context %s (it lives on %s)"
            % (self.name, ctx, list(arr_dict.keys())))

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        ov = _overrides()
        if ov is not None and id(self) in ov:
            return ov[id(self)]
        return self._check_and_get(self._data, ctx)

    def list_data(self) -> List[NDArray]:
        self._check_and_get(self._data, list)
        return list(self._data.values())

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter %s because "
                "grad_req='null'" % self.name)
        return self._check_and_get(self._grad, ctx)

    def list_grad(self) -> List[NDArray]:
        if self._data is not None and self._grad is None:
            raise RuntimeError("grad_req='null' for Parameter %s" % self.name)
        self._check_and_get(self._grad, list)
        return list(self._grad.values())

    def list_ctx(self) -> List[Context]:
        if self._data is None:
            if self._deferred_init is not None:
                return list(self._deferred_init[1])
            raise RuntimeError("Parameter %s has not been initialized"
                               % self.name)
        return list(self._data.keys())

    def set_data(self, data):
        """Set data on all contexts (reference: Parameter.set_data)."""
        self.shape = data.shape  # validates compatibility
        if self._data is None:
            if self._deferred_init is None:
                raise RuntimeError("initialize Parameter %s first" % self.name)
            # materialize directly from the given value
            _, ctx, _ = self._deferred_init
            self._data = OrderedDict()
            value = data.asnumpy() if isinstance(data, NDArray) else _np.asarray(data)
            for c in ctx:
                self._data[c] = _nd_mod.array(value, ctx=c, dtype=self.dtype)
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()
            return
        for arr in self._data.values():
            if isinstance(data, NDArray):
                # copyto, not a raw _set_jax of data's array: on the same
                # device+dtype that would alias data's buffer, and a
                # donated alias (compiled-step lane) dies with the donor
                data.copyto(arr)
            else:
                arr[:] = data

    def zero_grad(self):
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0

    def reset_ctx(self, ctx):
        """Move parameter to new context(s) (reference: reset_ctx)."""
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            value = next(iter(self._data.values())).asnumpy()
            self._data = OrderedDict(
                (c, _nd_mod.array(value, ctx=c, dtype=self.dtype)) for c in ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init is not None:
            init, _, default_init = self._deferred_init
            self._deferred_init = (init, list(ctx), default_init)
        else:
            raise ValueError("Cannot reset context for uninitialized "
                             "Parameter %s" % self.name)

    def cast(self, dtype):
        self.dtype = _norm_dtype(dtype)
        if self._data is None:
            return
        for c in list(self._data.keys()):
            self._data[c] = self._data[c].astype(dtype)
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        """Symbol variable for this parameter (symbol API compat)."""
        from ..symbol import Variable
        return Variable(self.name)

    def _reduce(self) -> NDArray:
        """Average over contexts → cpu (reference: Parameter._reduce)."""
        vals = self.list_data()
        out = vals[0].asnumpy().astype(_np.float64)
        for v in vals[1:]:
            out = out + v.asnumpy()
        out /= len(vals)
        return _nd_mod.array(out.astype(self.dtype), ctx=cpu())


class Constant(Parameter):
    """Non-trainable constant (reference: gluon.Constant)."""

    def __init__(self, value, name: Optional[str] = None):
        if isinstance(value, NDArray):
            value = value.asnumpy()
        value = _np.asarray(value)
        self.value = value
        super().__init__(name=name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=init_mod.Constant(value.tolist()))


class ParameterDict:
    """Ordered name→Parameter mapping (reference: gluon.ParameterDict; in
    2.x collect_params returns a plain dict — this class supports both
    surfaces: mapping protocol + initialize/zero_grad/save/load helpers)."""

    def __init__(self, prefix: str = "", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    # -- mapping protocol --------------------------------------------------
    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __setitem__(self, key, val):
        self._params[key] = val

    def __contains__(self, key):
        return key in self._params

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        body = "\n".join("  %s" % p for p in self._params.values())
        return "ParameterDict(\n%s\n)" % body

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def get(self, name, **kwargs) -> Parameter:
        """v1.x layer style: fetch-or-create `self.params.get('weight', ...)`."""
        full = self._prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    param.shape = v
            return param
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def update(self, other):
        if isinstance(other, ParameterDict):
            other = other._params
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("Cannot update: duplicate Parameter name %s"
                                 % k)
            self._params[k] = v

    # -- bulk ops ----------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False):
        default = init_mod.create(init) if init is not None else init_mod.Uniform()
        for param in self._params.values():
            param.initialize(None, ctx=ctx, default_init=default,
                             force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix: str = ""):
        arg = {}
        for p in self._params.values():
            weight = p._reduce()
            name = p.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = weight
        _nd_mod.save(filename, arg)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix: str = "",
             cast_dtype=False, dtype_source="current"):
        loaded = _nd_mod.load(filename)
        loaded = {(restore_prefix + k[4:]) if k.startswith(("arg:", "aux:"))
                  else restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise AssertionError(
                        "Parameter %s is missing in file %s" % (name, filename))
        for name, value in loaded.items():
            if name not in self._params:
                if not ignore_extra:
                    raise AssertionError(
                        "Parameter %s loaded from %s is not present in this "
                        "ParameterDict" % (name, filename))
                continue
            param = self._params[name]
            if cast_dtype and dtype_source == "saved":
                param.cast(value.dtype)
            elif cast_dtype:
                value = value.astype(param.dtype)
            if param._data is None and param._deferred_init is None and ctx is not None:
                param.initialize(ctx=ctx)
            param.set_data(value)
