"""Gluon: the imperative/hybrid high-level API.

Reference: python/mxnet/gluon/__init__.py — Block/HybridBlock/SymbolBlock,
Parameter/Constant/ParameterDict, Trainer, nn, rnn, loss, data, model_zoo,
utils, contrib; gluon.metric re-exports mx.metric (2.x move).
"""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import utils
from . import contrib
from . import data
from . import rnn
from . import model_zoo
from .. import metric

__all__ = ["Parameter", "Constant", "ParameterDict",
           "DeferredInitializationError", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "loss", "utils", "data", "rnn", "model_zoo", "metric"]
