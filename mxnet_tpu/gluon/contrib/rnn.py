"""gluon.contrib.rnn (reference: python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py — Conv1D/2D/3DLSTMCell family — and rnn_cell.py —
VariationalDropoutCell, LSTMPCell)."""
from __future__ import annotations

from ... import initializer as init_mod
from ...ndarray.ndarray import invoke
from ..parameter import Parameter
from ..rnn.rnn_cell import RecurrentCell

__all__ = ["Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "VariationalDropoutCell", "LSTMPCell"]


class _ConvLSTMCell(RecurrentCell):
    """ConvLSTM (Shi et al. 2015): the LSTM matmuls become convolutions,
    states carry spatial maps (reference: contrib.rnn._ConvRNNCell/
    _ConvLSTMCell).  input: (N, C, *spatial); hidden: (N, H, *spatial)."""

    _ndim = 2

    def __init__(self, input_shape, hidden_channels, i2h_kernel=3,
                 h2h_kernel=3, activation="tanh", **kwargs):
        super().__init__(**kwargs)
        nd_ = self._ndim
        self._input_shape = tuple(input_shape)   # (C, *spatial)
        self._hc = hidden_channels
        k = (i2h_kernel,) * nd_ if isinstance(i2h_kernel, int) \
            else tuple(i2h_kernel)
        hk = (h2h_kernel,) * nd_ if isinstance(h2h_kernel, int) \
            else tuple(h2h_kernel)
        # pad is derived as k//2 for BOTH convs, so both kernels must be
        # odd or the i2h/h2h spatial dims diverge
        assert all(x % 2 == 1 for x in hk), \
            "h2h_kernel must be odd to conserve spatial dims"
        assert all(x % 2 == 1 for x in k), \
            "i2h_kernel must be odd to conserve spatial dims"
        self._i2h_kernel, self._h2h_kernel = k, hk
        self._i2h_pad = tuple(x // 2 for x in k)
        self._h2h_pad = tuple(x // 2 for x in hk)
        self._activation = activation
        C = self._input_shape[0]
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_channels, C) + k)
        self.h2h_weight = Parameter(
            "h2h_weight", shape=(4 * hidden_channels, hidden_channels) + hk)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_channels,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_channels,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        spatial = self._input_shape[1:]
        shape = (batch_size, self._hc) + spatial
        return [{"shape": shape}, {"shape": shape}]

    def forward(self, inputs, states):
        ctx = inputs.context
        i2h = invoke("Convolution", inputs, self.i2h_weight.data(ctx),
                     self.i2h_bias.data(ctx), kernel=self._i2h_kernel,
                     pad=self._i2h_pad, num_filter=4 * self._hc)
        h2h = invoke("Convolution", states[0], self.h2h_weight.data(ctx),
                     self.h2h_bias.data(ctx), kernel=self._h2h_kernel,
                     pad=self._h2h_pad, num_filter=4 * self._hc)
        gates = i2h + h2h
        sl = gates.split(num_outputs=4, axis=1)
        i = sl[0].sigmoid()
        f = sl[1].sigmoid()
        g = invoke("Activation", sl[2], act_type=self._activation)
        o = sl[3].sigmoid()
        next_c = f * states[1] + i * g
        next_h = o * invoke("Activation", next_c,
                            act_type=self._activation)
        return next_h, [next_h, next_c]


class Conv1DLSTMCell(_ConvLSTMCell):
    _ndim = 1


class Conv2DLSTMCell(_ConvLSTMCell):
    _ndim = 2


class Conv3DLSTMCell(_ConvLSTMCell):
    _ndim = 3


class VariationalDropoutCell(RecurrentCell):
    """Variational (same-mask-every-step) dropout around a base cell
    (reference: contrib.rnn.VariationalDropoutCell; Gal & Ghahramani).
    Masks are drawn ONCE per unroll (reset clears them)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self._mask_i = self._mask_s = self._mask_o = None
        base_cell._modified = True

    def reset(self):
        super().reset()
        self._mask_i = self._mask_s = self._mask_o = None
        base = getattr(self, "base_cell", None)   # called from __init__ too
        if base is not None:
            base._modified = False
            base.reset()
            base._modified = True

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        out = self.base_cell.begin_state(batch_size, func, **kwargs)
        self.base_cell._modified = True
        return out

    def _mask(self, cached, like, p):
        from ... import autograd
        if p == 0.0 or not autograd.is_training():
            return None
        if cached is None or cached.shape != like.shape:
            keep = invoke("_random_bernoulli", prob=1.0 - p,
                          shape=like.shape, dtype=str(like.dtype))
            cached = keep / (1.0 - p)
        return cached

    def forward(self, inputs, states):
        self._mask_i = self._mask(self._mask_i, inputs, self._di)
        if self._mask_i is not None:
            inputs = inputs * self._mask_i
        if self._ds:
            self._mask_s = self._mask(self._mask_s, states[0], self._ds)
            if self._mask_s is not None:
                states = [states[0] * self._mask_s] + list(states[1:])
        self.base_cell._modified = False
        out, next_states = self.base_cell(inputs, states)
        self.base_cell._modified = True
        self._mask_o = self._mask(self._mask_o, out, self._do)
        if self._mask_o is not None:
            out = out * self._mask_o
        return out, next_states


class LSTMPCell(RecurrentCell):
    """LSTM with a hidden-state projection (reference: contrib.rnn.
    LSTMPCell; Sak et al. 2014 — h = W_r · o⊙tanh(c), shrinking the
    recurrent width)."""

    def __init__(self, hidden_size, projection_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        nh, npj = hidden_size, projection_size
        self.i2h_weight = Parameter("i2h_weight", shape=(4 * nh, input_size),
                                    allow_deferred_init=True)
        self.h2h_weight = Parameter("h2h_weight", shape=(4 * nh, npj))
        self.h2r_weight = Parameter("h2r_weight", shape=(npj, nh))
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * nh,),
                                  init=init_mod.Zero())
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * nh,),
                                  init=init_mod.Zero())

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def forward(self, inputs, states):
        ctx = inputs.context
        nh = self._hidden_size
        i2h = invoke("FullyConnected", inputs, self.i2h_weight.data(ctx),
                     self.i2h_bias.data(ctx), num_hidden=4 * nh)
        h2h = invoke("FullyConnected", states[0], self.h2h_weight.data(ctx),
                     self.h2h_bias.data(ctx), num_hidden=4 * nh)
        gates = i2h + h2h
        sl = gates.split(num_outputs=4, axis=1)
        i, f = sl[0].sigmoid(), sl[1].sigmoid()
        g, o = sl[2].tanh(), sl[3].sigmoid()
        next_c = f * states[1] + i * g
        hidden = o * next_c.tanh()
        next_r = invoke("FullyConnected", hidden,
                        self.h2r_weight.data(ctx), None, no_bias=True,
                        num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
