"""Gluon Estimator fit loop (reference:
python/mxnet/gluon/contrib/estimator/estimator.py (class Estimator))."""
from __future__ import annotations

import copy

from .... import autograd, metric as metric_mod
from ....base import MXNetError
from ....device import current_context
from ... import Trainer
from ... import loss as gloss
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler,
                            LoggingHandler)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Estimator:
    """High-level fit/evaluate over a Gluon net (reference: Estimator).

    estimator = Estimator(net, loss=SoftmaxCrossEntropyLoss(),
                          train_metrics=mx.metric.Accuracy(),
                          trainer=Trainer(...))
    estimator.fit(train_data, val_data, epochs=3)
    """

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 trainer=None, context=None):
        self.net = net
        if not isinstance(loss, gloss.Loss):
            raise MXNetError("loss must be a gluon Loss; got %r"
                             % (type(loss).__name__,))
        self.loss = loss
        self.train_metrics = _as_list(train_metrics)
        if not self.train_metrics:
            self.train_metrics = [metric_mod.Accuracy()]
        self.train_metrics.append(metric_mod.Loss("train loss"))
        # val metrics mirror the train ones — deepcopy keeps constructor
        # config (TopKAccuracy(top_k=...), Accuracy(axis=...))
        self.val_metrics = _as_list(val_metrics)
        if not self.val_metrics:
            self.val_metrics = []
            for m in self.train_metrics:
                if isinstance(m, metric_mod.Loss):
                    self.val_metrics.append(
                        metric_mod.Loss("validation loss"))
                else:
                    vm = copy.deepcopy(m)
                    vm.reset()
                    self.val_metrics.append(vm)
        self.context = context or current_context()
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.stop_training = False

    # -- evaluation ---------------------------------------------------------
    def evaluate_batch(self, batch, metrics):
        data, label = (b.as_in_context(self.context) for b in batch[:2])
        pred = self.net(data)
        loss = self.loss(pred, label)
        for m in metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)

    def evaluate(self, val_data, batch_axis=0):
        for m in self.val_metrics:
            m.reset()
        # DataIter.__iter__ returns self without rewinding: reset here or
        # the per-epoch ValidationHandler iterates an exhausted iterator
        # from epoch 2 on and validation metrics silently freeze
        if hasattr(val_data, "reset"):
            val_data.reset()
        for batch in val_data:
            batch = batch if isinstance(batch, (list, tuple)) \
                else (batch.data[0], batch.label[0])
            self.evaluate_batch(batch, self.val_metrics)
        return self.val_metrics

    # -- training -----------------------------------------------------------
    def fit_batch(self, batch, batch_axis=0):
        data, label = (b.as_in_context(self.context) for b in batch[:2])
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        return data, label, pred, loss

    def fit(self, train_data, val_data=None, epochs=None,
            event_handlers=None, batches=None, batch_axis=0):
        if epochs is None and batches is None:
            epochs = 1
        handlers = self._prepare_handlers(val_data, epochs, batches,
                                          _as_list(event_handlers))
        # validation runs FIRST at each boundary so user handlers
        # monitoring a val metric read THIS epoch's value (reference
        # sorts handlers the same way)
        def _ordered(cls):
            hs = [h for h in handlers if isinstance(h, cls)]
            return ([h for h in hs if isinstance(h, ValidationHandler)]
                    + [h for h in hs
                       if not isinstance(h, ValidationHandler)])
        tb, te = _ordered(TrainBegin), _ordered(TrainEnd)
        eb, ee = _ordered(EpochBegin), _ordered(EpochEnd)
        bb, be = _ordered(BatchBegin), _ordered(BatchEnd)

        self.stop_training = False
        for h in tb:
            h.train_begin(self)
        while not self.stop_training:
            for h in eb:
                h.epoch_begin(self)
            if hasattr(train_data, "reset"):
                train_data.reset()
            for batch in train_data:
                batch = batch if isinstance(batch, (list, tuple)) \
                    else (batch.data[0], batch.label[0])
                for h in bb:
                    h.batch_begin(self, batch=batch)
                data, label, pred, loss = self.fit_batch(batch,
                                                         batch_axis)
                self.trainer.step(data.shape[batch_axis])
                for h in be:
                    if h.batch_end(self, batch=batch, pred=pred,
                                   label=label, loss=loss):
                        self.stop_training = True
                if self.stop_training:
                    break
            for h in ee:
                if h.epoch_end(self):
                    self.stop_training = True
        for h in te:
            h.train_end(self)

    def _prepare_handlers(self, val_data, epochs, batches, handlers):
        # defaults mirror the reference: stopping + metric + validation +
        # logging unless the user supplied their own of that kind
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(self.train_metrics))
        if val_data is not None and \
                not any(isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(metrics=self.train_metrics))
        return handlers
