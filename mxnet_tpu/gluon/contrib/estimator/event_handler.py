"""Estimator event handlers (reference:
python/mxnet/gluon/contrib/estimator/event_handler.py)."""
from __future__ import annotations

import logging
import os

from .... import metric as metric_mod
from ....base import MXNetError


def _single_metric_value(monitor, what):
    name, value = monitor.get()
    if isinstance(value, (list, tuple)):
        raise MXNetError(
            "%s needs a SINGLE metric to monitor; got a composite "
            "(%r) - pass one of its children" % (what, name))
    return name, value


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch/max_batch (reference: StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    """Reset train metrics per epoch, update per batch (reference:
    MetricHandler)."""

    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation on an interval (reference: ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1,
                 batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                     BatchBegin, BatchEnd):
    """Train progress logging (reference: LoggingHandler)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training end")

    def _fmt(self):
        return ", ".join("%s: %.4f" % m.get() for m in self.metrics)

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            self.logger.info("[Epoch %d][Batch %d] %s",
                             self.current_epoch, self.batch_index,
                             self._fmt())

    def epoch_begin(self, estimator, *args, **kwargs):
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        self.logger.info("[Epoch %d] %s", self.current_epoch, self._fmt())
        self.current_epoch += 1


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save parameters (+trainer state) per epoch; optionally only on
    monitored-metric improvement (reference: CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="auto", save_best=False, epoch_period=1):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.current_epoch = 0
        if monitor is not None:
            name = _single_metric_value(monitor, "CheckpointHandler")[0]
        else:
            name = ""
        if mode == "auto":
            mode = "max" if "acc" in name or "f1" in name else "min"
        self.mode = mode
        self.best = float("-inf") if mode == "max" else float("inf")

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)

    def _improved(self, value):
        return value > self.best if self.mode == "max" \
            else value < self.best

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.current_epoch % self.epoch_period:
            return
        prefix = os.path.join(self.model_dir, self.model_prefix)
        estimator.net.save_parameters(
            "%s-epoch%d.params" % (prefix, self.current_epoch))
        if estimator.trainer is not None:
            estimator.trainer.save_states(
                "%s-epoch%d.states" % (prefix, self.current_epoch))
        if self.save_best and self.monitor is not None:
            value = _single_metric_value(self.monitor,
                                         "CheckpointHandler")[1]
            if self._improved(value):
                self.best = value
                estimator.net.save_parameters(
                    "%s-best.params" % prefix)


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stop when the monitored metric stops improving (reference:
    EarlyStoppingHandler)."""

    def __init__(self, monitor, min_delta=0.0, patience=0, mode="auto"):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        name = _single_metric_value(monitor, "EarlyStoppingHandler")[0]
        if mode == "auto":
            mode = "max" if "acc" in name or "f1" in name else "min"
        self.mode = mode
        self.best = float("-inf") if mode == "max" else float("inf")
        self.wait = 0
        self.stop_training = False
        self.stopped_epoch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stop_training = False

    def _improved(self, value):
        if self.mode == "max":
            return value > self.best + self.min_delta
        return value < self.best - self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        value = _single_metric_value(self.monitor,
                                     "EarlyStoppingHandler")[1]
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
        self.current_epoch += 1
        return self.stop_training
