"""Gluon Estimator (reference: python/mxnet/gluon/contrib/estimator/).

The high-level fit loop over a Gluon net: metrics, validation, and an
event-handler pipeline (train/epoch/batch begin+end hooks) with the
stock handlers (logging, checkpointing, early stopping, validation).
"""
from .estimator import Estimator
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler,
                            LoggingHandler, CheckpointHandler,
                            EarlyStoppingHandler)

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin",
           "EpochEnd", "BatchBegin", "BatchEnd", "StoppingHandler",
           "MetricHandler", "ValidationHandler", "LoggingHandler",
           "CheckpointHandler", "EarlyStoppingHandler"]
