"""gluon.contrib.nn (reference: python/mxnet/gluon/contrib/nn/
basic_layers.py — Concurrent, HybridConcurrent, Identity,
SparseEmbedding, SyncBatchNorm, PixelShuffle1D/2D/3D)."""
from __future__ import annotations

from ...ndarray.ndarray import invoke
from .. import nn as _nn
from ..block import Block, HybridBlock

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle1D", "PixelShuffle2D",
           "PixelShuffle3D"]


class Concurrent(_nn.Concatenate):
    """Run children on the SAME input, concat outputs (reference:
    contrib.nn.Concurrent — renamed nn.Concatenate in 2.x; same block)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(axis=axis, **kwargs)
        self.axis = axis


class HybridConcurrent(_nn.HybridConcatenate):
    """Hybridizable Concurrent (2.x: nn.HybridConcatenate)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(axis=axis, **kwargs)
        self.axis = axis


Identity = _nn.Identity


class SparseEmbedding(Block):
    """Embedding with row-sparse gradients (reference:
    contrib.nn.SparseEmbedding; here sparse_grad=True Embedding — the
    rowsparse path is the op's gather VJP)."""

    def __init__(self, input_dim, output_dim, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "sparse_grad": True}
        from ..parameter import Parameter
        self.weight = Parameter("weight", shape=(input_dim, output_dim),
                                dtype=dtype, stype="row_sparse",
                                grad_stype="row_sparse")

    def forward(self, x):
        return invoke("Embedding", x, self.weight.data(x.context),
                      **self._kwargs)


# contrib and nn share ONE SyncBatchNorm (2.x moved it to nn); the v1.x
# contrib signature (num_devices) is the nn one
SyncBatchNorm = _nn.SyncBatchNorm


def _pixel_shuffle(ndim):
    class _PixelShuffle(HybridBlock):
        def __init__(self, factor, **kwargs):
            super().__init__(**kwargs)
            self._factor = (factor,) * ndim if isinstance(factor, int) \
                else tuple(factor)

        def hybrid_forward(self, F, x):
            # registry-routed reshape/transpose/reshape (the reference's
            # own decomposition) so the op sequence records on the
            # autograd tape AND serializes through the symbol tracer
            f = self._factor
            N, C = x.shape[0], x.shape[1]
            spatial = tuple(x.shape[2:])
            n_f = 1
            for fi in f:
                n_f *= int(fi)
            newC = C // n_f
            x = x.reshape((N, newC) + tuple(f) + spatial)
            perm = [0, 1]
            for i in range(ndim):
                perm += [2 + ndim + i, 2 + i]
            x = x.transpose(tuple(perm))
            out_sp = tuple(d * fi for d, fi in zip(spatial, f))
            return x.reshape((N, newC) + out_sp)

        def __repr__(self):
            return "%s(factor=%s)" % (type(self).__name__, (self._factor,))
    return _PixelShuffle


PixelShuffle1D = _pixel_shuffle(1)
PixelShuffle1D.__name__ = "PixelShuffle1D"
PixelShuffle1D.__doc__ = """Upsample 1-D by channel-to-width shuffle
(reference: contrib.nn.PixelShuffle1D)."""
PixelShuffle2D = _pixel_shuffle(2)
PixelShuffle2D.__name__ = "PixelShuffle2D"
PixelShuffle2D.__doc__ = """Sub-pixel convolution upsampling: (N, C*f1*f2,
H, W) -> (N, C, H*f1, W*f2) (reference: contrib.nn.PixelShuffle2D)."""
PixelShuffle3D = _pixel_shuffle(3)
PixelShuffle3D.__name__ = "PixelShuffle3D"
PixelShuffle3D.__doc__ = """3-D sub-pixel shuffle (reference:
contrib.nn.PixelShuffle3D)."""
