"""gluon.contrib — experimental blocks (reference:
python/mxnet/gluon/contrib/: nn/basic_layers.py, rnn/conv_rnn_cell.py,
rnn/rnn_cell.py, estimator/)."""
from . import nn         # noqa: F401
from . import rnn        # noqa: F401
from . import estimator  # noqa: F401

__all__ = ["nn", "rnn", "estimator"]
