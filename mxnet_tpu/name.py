"""mx.name — NameManager / Prefix (reference: python/mxnet/name.py).

The v1.x auto-naming stack: symbols created without an explicit name ask
the CURRENT NameManager; ``with mx.name.Prefix('stage1_'):`` prepends a
prefix to every auto-generated name inside the scope (how the reference
model zoo keeps per-stage parameter names unique)."""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Scoped auto-namer (reference: name.NameManager)."""

    _current = threading.local()

    def __init__(self):
        self._counter: Dict[str, int] = {}
        self._old_manager: Optional["NameManager"] = None

    def get(self, name: Optional[str], hint: str) -> str:
        """Return `name` or generate `hint%d` (reference: NameManager.get)."""
        if name is not None:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return "%s%d" % (hint, n)

    def __enter__(self):
        self._old_manager = current()
        NameManager._current.value = self
        return self

    def __exit__(self, *exc):
        NameManager._current.value = self._old_manager
        return False


class Prefix(NameManager):
    """Prefix every auto name (reference: name.Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current() -> NameManager:
    mgr = getattr(NameManager._current, "value", None)
    if mgr is None:
        mgr = NameManager()
        NameManager._current.value = mgr
    return mgr
