"""Optimizer package (reference: python/mxnet/optimizer/)."""
from .optimizer import (Optimizer, Updater, get_updater, register, create,
                        SGD, NAG, Adam, AdamW, RMSProp, AdaGrad, AdaDelta,
                        Ftrl, LAMB, LARS, Signum, SignSGD, DCASGD, Test)

__all__ = ["Optimizer", "Updater", "get_updater", "register", "create",
           "SGD", "NAG", "Adam", "AdamW", "RMSProp", "AdaGrad", "AdaDelta",
           "Ftrl", "LAMB", "LARS", "Signum", "SignSGD", "DCASGD", "Test"]
