"""Optimizers.

Reference: python/mxnet/optimizer/optimizer.py [v1.x] / per-file [2.x]
(class Optimizer — registry, lr/wd mults, num_update bookkeeping,
create_state, update_multi_precision; SGD, NAG, Adam, RMSProp, AdaGrad,
AdaDelta, Ftrl, LAMB, LARS, Signum, DCASGD, Test; get_updater for the
kvstore server path).

TPU-native: every update dispatches one fused jitted op from
ops/optimizer.py (the reference's hand-written CUDA kernels in
src/operator/optimizer_op.cc become XLA-fused elementwise chains).
Multi-precision keeps an fp32 master copy when the weight is bf16/fp16
(reference: MP_SGD kernels; SURVEY.md AMP row).
"""
from __future__ import annotations

import math
import pickle
import warnings
from typing import Any, Dict, Optional

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, invoke
from .. import ndarray as nd
from ..lr_scheduler import LRScheduler

__all__ = ["Optimizer", "Updater", "get_updater", "register", "create"]


def _aggregate_default(n):
    """Default aggregate_num for fused-capable optimizers.  The
    MX_OPTIMIZER_AGGREGATE env knob overrides: 0 opts out (per-param
    loop, the pre-fusion behavior), any other integer caps the number of
    (weight, grad, state) triples fused into one jitted pytree dispatch."""
    from ..base import get_env
    v = get_env("MX_OPTIMIZER_AGGREGATE", None, int)
    # unset reads back as the catalog's "" default: keep the class default
    if not isinstance(v, int) or v < 0:
        return n
    return v


def _chunks(seq, n):
    if n <= 0 or n >= len(seq):
        yield seq
        return
    for i in range(0, len(seq), n):
        yield seq[i:i + n]


class Optimizer:
    """Base optimizer (reference: class Optimizer)."""

    opt_registry: Dict[str, type] = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None, aggregate_num=0, use_fused_step=True):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._all_index_update_counts = {0: {}}
        self._index_update_count = self._all_index_update_counts[0]
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = ()
        self.param_dict = param_dict if param_dict else {}

    # -- registry ----------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (_np.float16,
                                                     _np.dtype("bfloat16")):
            weight_master_copy = weight.astype(_np.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        return self.create_state(index, weight)

    # -- update ------------------------------------------------------------
    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and isinstance(state, tuple) and \
                len(state) == 2 and isinstance(state[1], NDArray) and \
                state[1].dtype == _np.float32 and weight.dtype != _np.float32:
            inner_state, weight32 = state
            grad32 = grad.astype(_np.float32)
            self.update(index, weight32, grad32, inner_state)
            weight._set_jax(weight32._jax.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    # list-form dispatch (2.x update signature takes lists)
    def _normalize(self, indices, weights, grads, states):
        if isinstance(weights, NDArray):
            return [indices], [weights], [grads], [states]
        return indices, weights, grads, states

    # -- fused multi-tensor apply (ISSUE 3 tentpole a) ---------------------
    # The reference reaches one-kernel-per-group via multi_sgd_update /
    # multi_adamw fleets gated on aggregate_num; here fused-capable
    # optimizers apply the whole (weight, grad, state) batch as ONE jitted
    # pytree update (ops/optimizer.py tree kernels), with lr_mult/wd_mult/
    # num_update bookkeeping folded in as per-leaf scalars.

    def fused_update(self, indices, weights, grads, states):
        """Apply the whole batch in O(1) jitted dispatches (O(#chunks)
        when aggregate_num caps the group).  Returns False when this
        optimizer has no tree kernel — callers then fall back to the
        per-param update loop."""
        return False

    # -- whole-step compiled lane (ISSUE 7) --------------------------------
    def _compiled_spec(self):
        """Functional description of this optimizer's update for the
        whole-step compiled lane (mxnet_tpu.step.CompiledStep / the
        Module compiled fit step): a dict with

          ``kind``      — ops.optimizer tree-body name,
          ``static``    — trace-static kwargs (momentum, betas, ...),
          ``unpack``    — ``(state, mp) -> (inner_state_tuple, w32)``,
                          the same layout split _fused_apply uses,
          ``n_state``   — number of inner state columns,
          ``lr_fn``     — optional ``(index, lr) -> effective lr`` (host,
                          per step; bias correction folds in here so the
                          compiled trace sees lr as a traced scalar),
          ``decay_fn``  — optional ``(index, lr, wd) -> decoupled decay``.

        Returns None when the optimizer has no pure tree kernel — the
        compiled lane then falls back to the eager pipeline."""
        return None

    def _is_mp_state(self, weight, state):
        """Same predicate update_multi_precision routes on: a (inner,
        fp32-master) state pair for a low-precision weight."""
        return (self.multi_precision and isinstance(state, tuple) and
                len(state) == 2 and isinstance(state[1], NDArray) and
                state[1].dtype == _np.float32 and
                weight.dtype != _np.float32)

    def _fused_apply(self, kind, indices, weights, grads, states, unpack,
                     lr_fn=None, decay_fn=None, **static):
        """Shared fused-apply skeleton: num_update bookkeeping, per-leaf
        lr/wd, multi-precision grouping, aggregate_num chunking, ONE
        tree_apply dispatch per chunk, in-place write-back.

        ``unpack(state, mp) -> (inner_state_tuple, weight32_or_None)``
        flattens this optimizer's state layout; ``lr_fn(pos)`` /
        ``decay_fn(pos)`` (pos indexes into `indices`) let Adam-family
        classes fold bias correction / decoupled decay into the per-leaf
        scalars exactly as their per-param update does.
        """
        from ..ops.optimizer import tree_apply
        self._update_count(indices)
        lrs = self._get_lrs(indices)
        wds = self._get_wds(indices)
        groups: Dict[Any, list] = {}
        for pos in range(len(indices)):
            mp = self._is_mp_state(weights[pos], states[pos])
            # one jitted program spans one device: group2ctx model
            # parallelism puts params on different devices — each gets its
            # own fused dispatch (still O(#devices), not O(#params))
            dev = (weights[pos].context.jax_device,
                   grads[pos].context.jax_device)
            groups.setdefault((mp, dev), []).append(pos)
        for (mp, _dev), poss in groups.items():
            for chunk in _chunks(poss, self.aggregate_num):
                ws = [weights[p] for p in chunk]
                inners, w32s = [], []
                for p in chunk:
                    inner, w32 = unpack(states[p], mp)
                    inners.append(inner)
                    w32s.append(w32)
                state_cols = [[inn[j] for inn in inners]
                              for j in range(len(inners[0]))]
                arrays = [[w._jax for w in ws],
                          [grads[p]._jax for p in chunk]]
                arrays += [[s._jax for s in col] for col in state_cols]
                arrays.append([s._jax for s in w32s] if mp else None)
                eff_lrs = [lr_fn(p, lrs[p]) if lr_fn else lrs[p]
                           for p in chunk]
                decays = [decay_fn(p, lrs[p], wds[p]) for p in chunk] \
                    if decay_fn else None
                out_w, out_states, out_w32 = tree_apply(
                    kind, arrays, eff_lrs, decays,
                    wds=tuple(wds[p] for p in chunk),
                    rescale_grad=self.rescale_grad,
                    clip_gradient=_clip(self.clip_gradient),
                    mp=mp, **static)
                for j, w in enumerate(ws):
                    w._set_jax(out_w[j])
                if out_states:
                    for col, outs in zip(state_cols, out_states):
                        for j, s in enumerate(col):
                            s._set_jax(outs[j])
                if mp and out_w32 is not None:
                    for j, s in enumerate(w32s):
                        s._set_jax(out_w32[j])
        return True

    # -- lr / wd plumbing --------------------------------------------------
    @property
    def learning_rate(self):
        """Current base lr — scheduler value without per-param multipliers
        (reference: Optimizer.learning_rate property)."""
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = args_lr_mult.copy()

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight") or n.endswith(".weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _set_current_context(self, device_id):
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        return ret

    def __setstate__(self, state):
        self.__dict__.update(state)


register = Optimizer.register


def create(name, **kwargs):
    """Reference: mx.optimizer.create."""
    if isinstance(name, Optimizer):
        return name
    return Optimizer.create_optimizer(name, **kwargs)


def _clip(value):
    return -1.0 if value is None else value


@register
class SGD(Optimizer):
    """SGD with momentum (reference: optimizer.SGD → sgd_update /
    sgd_mom_update / mp_* fused kernels)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=True,
                 **kwargs):
        kwargs.setdefault("aggregate_num", _aggregate_default(64))
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_update(self, indices, weights, grads, states):
        has_mom = self.momentum != 0.0

        def unpack(state, mp):
            inner = state[0] if mp else state
            return ((inner,) if has_mom else ()), (state[1] if mp else None)

        extra = {"momentum": self.momentum} if has_mom else {}
        return self._fused_apply("sgd_mom" if has_mom else "sgd", indices,
                                 weights, grads, states, unpack, **extra)

    def _compiled_spec(self):
        has_mom = self.momentum != 0.0

        def unpack(state, mp):
            inner = state[0] if mp else state
            return ((inner,) if has_mom else ()), (state[1] if mp else None)

        return {"kind": "sgd_mom" if has_mom else "sgd",
                "static": {"momentum": self.momentum} if has_mom else {},
                "unpack": unpack, "n_state": 1 if has_mom else 0}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient))
        if getattr(grad, "stype", "default") == "row_sparse":
            if self.lazy_update:
                # reference SGDUpdateRspImpl: only gradient rows are touched
                if state is not None:
                    invoke("_sparse_sgd_mom_update", weight, grad.data,
                           grad.indices, state, momentum=self.momentum, **kw)
                else:
                    invoke("_sparse_sgd_update", weight, grad.data,
                           grad.indices, **kw)
                return
            grad = grad.tostype("default")
        if state is not None:
            invoke("sgd_mom_update", weight, grad, state,
                   momentum=self.momentum, **kw)
        else:
            invoke("sgd_update", weight, grad, **kw)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: optimizer.NAG)."""

    def __init__(self, learning_rate=0.1, momentum=0.0, **kwargs):
        kwargs.setdefault("aggregate_num", _aggregate_default(64))
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            invoke("nag_mom_update", weight, grad, state,
                   momentum=self.momentum, **kw)
        else:
            invoke("sgd_update", weight, grad, **kw)

    def fused_update(self, indices, weights, grads, states):
        has_mom = self.momentum != 0.0

        def unpack(state, mp):
            inner = state[0] if mp else state
            return ((inner,) if has_mom else ()), (state[1] if mp else None)

        extra = {"momentum": self.momentum} if has_mom else {}
        return self._fused_apply("nag_mom" if has_mom else "sgd", indices,
                                 weights, grads, states, unpack, **extra)

    def _compiled_spec(self):
        has_mom = self.momentum != 0.0

        def unpack(state, mp):
            inner = state[0] if mp else state
            return ((inner,) if has_mom else ()), (state[1] if mp else None)

        return {"kind": "nag_mom" if has_mom else "sgd",
                "static": {"momentum": self.momentum} if has_mom else {},
                "unpack": unpack, "n_state": 1 if has_mom else 0}


@register
class Adam(Optimizer):
    """Reference: optimizer.Adam → adam_update fused kernel, with the
    bias-correction folded into lr like the reference."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        kwargs.setdefault("aggregate_num", _aggregate_default(64))
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def fused_update(self, indices, weights, grads, states):
        def unpack(state, mp):
            mean, var = state[0] if mp else state
            return (mean, var), (state[1] if mp else None)

        def lr_fn(pos, lr):
            # bias correction folded into lr on host in float64 (t is a
            # host int after _update_count), exactly like update()
            t = self._index_update_count[indices[pos]]
            return lr * math.sqrt(1.0 - self.beta2 ** t) / \
                (1.0 - self.beta1 ** t)

        return self._fused_apply("adam", indices, weights, grads, states,
                                 unpack, lr_fn=lr_fn, beta1=self.beta1,
                                 beta2=self.beta2, epsilon=self.epsilon)

    def _compiled_spec(self):
        def unpack(state, mp):
            mean, var = state[0] if mp else state
            return (mean, var), (state[1] if mp else None)

        def lr_fn(index, lr):
            t = self._index_update_count[index]
            return lr * math.sqrt(1.0 - self.beta2 ** t) / \
                (1.0 - self.beta1 ** t)

        return {"kind": "adam",
                "static": {"beta1": self.beta1, "beta2": self.beta2,
                           "epsilon": self.epsilon},
                "unpack": unpack, "n_state": 2, "lr_fn": lr_fn}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        if getattr(grad, "stype", "default") == "row_sparse":
            if self.lazy_update:
                # reference AdamUpdateRspImpl: moments decay only on rows
                # the batch touched
                invoke("_sparse_adam_update", weight, grad.data, grad.indices,
                       mean, var, lr=lr, wd=wd, beta1=self.beta1,
                       beta2=self.beta2, epsilon=self.epsilon,
                       rescale_grad=self.rescale_grad,
                       clip_gradient=_clip(self.clip_gradient))
                return
            grad = grad.tostype("default")
        invoke("adam_update", weight, grad, mean, var, lr=lr, wd=wd,
               beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
               rescale_grad=self.rescale_grad,
               clip_gradient=_clip(self.clip_gradient))


@register
class AdamW(Optimizer):
    """Decoupled weight decay Adam (reference: contrib adamw_update;
    2.x optimizer.AdamW)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, correct_bias=True, **kwargs):
        kwargs.setdefault("aggregate_num", _aggregate_default(64))
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.correct_bias = correct_bias

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def fused_update(self, indices, weights, grads, states):
        def unpack(state, mp):
            mean, var = state[0] if mp else state
            return (mean, var), (state[1] if mp else None)

        def lr_fn(pos, lr):
            if not self.correct_bias:
                return lr
            t = self._index_update_count[indices[pos]]
            return lr * math.sqrt(1.0 - self.beta2 ** t) / \
                (1.0 - self.beta1 ** t)

        def decay_fn(pos, lr, wd):
            # DECOUPLED decay at the RAW lr (see update() below)
            return lr * wd

        return self._fused_apply("adamw", indices, weights, grads, states,
                                 unpack, lr_fn=lr_fn, decay_fn=decay_fn,
                                 beta1=self.beta1, beta2=self.beta2,
                                 epsilon=self.epsilon)

    def _compiled_spec(self):
        def unpack(state, mp):
            mean, var = state[0] if mp else state
            return (mean, var), (state[1] if mp else None)

        def lr_fn(index, lr):
            if not self.correct_bias:
                return lr
            t = self._index_update_count[index]
            return lr * math.sqrt(1.0 - self.beta2 ** t) / \
                (1.0 - self.beta1 ** t)

        return {"kind": "adamw",
                "static": {"beta1": self.beta1, "beta2": self.beta2,
                           "epsilon": self.epsilon},
                "unpack": unpack, "n_state": 2, "lr_fn": lr_fn,
                "decay_fn": lambda index, lr, wd: lr * wd}

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        step_lr = lr
        if self.correct_bias:
            coef1 = 1.0 - self.beta1 ** t
            coef2 = 1.0 - self.beta2 ** t
            step_lr = lr * math.sqrt(coef2) / coef1
        mean, var = state
        # DECOUPLED decay at the RAW lr (the reference class follows the
        # huggingface formulation: only the adam step carries the
        # bias-correction factor; coupling wd with it shrinks the decay
        # ~3x at t=1)
        invoke("adamw_update", weight, grad, mean, var, lr=step_lr,
               wd=0.0, eta=1.0, beta1=self.beta1, beta2=self.beta2,
               epsilon=self.epsilon, rescale_grad=self.rescale_grad,
               clip_gradient=_clip(self.clip_gradient))
        if wd:
            weight -= lr * wd * weight


@register
class RMSProp(Optimizer):
    """Reference: optimizer.RMSProp (centered=True → rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                    nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                    nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  gamma1=self.gamma1, epsilon=self.epsilon,
                  clip_gradient=_clip(self.clip_gradient),
                  clip_weights=_clip(self.clip_weights))
        if self.centered:
            n, g, delta = state
            invoke("rmspropalex_update", weight, grad, n, g, delta,
                   gamma2=self.gamma2, **kw)
        else:
            invoke("rmsprop_update", weight, grad, state, **kw)


@register
class AdaGrad(Optimizer):
    """Reference: optimizer.AdaGrad (history += g^2; w -= lr*g/sqrt(h+eps))."""

    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        # reference formula: history accumulates raw g^2; wd applied outside
        # the adaptive denominator (optimizer.AdaGrad)
        state += grad * grad
        div = grad / (state + self.float_stable_eps).sqrt()
        weight -= lr * (div + wd * weight)


@register
class AdaDelta(Optimizer):
    """Reference: optimizer.AdaDelta."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        grad = grad + wd * weight
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1.0 - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon).sqrt() /
                         (acc_g + self.epsilon).sqrt()) * grad
        acc_delta[:] = self.rho * acc_delta + \
            (1.0 - self.rho) * current_delta * current_delta
        weight -= current_delta


@register
class Ftrl(Optimizer):
    """Reference: optimizer.Ftrl → ftrl_update."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),   # z
                nd.zeros(weight.shape, ctx=weight.context))   # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        z, n = state
        invoke("ftrl_update", weight, grad, z, n, lr=lr, lamda1=self.lamda1,
               beta=self.beta, wd=wd, rescale_grad=self.rescale_grad,
               clip_gradient=_clip(self.clip_gradient))


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for large-batch BERT (reference:
    optimizer.LAMB → lamb_update_phase1/2; SURVEY.md M6)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        g_update = invoke("lamb_update_phase1", grad, weight, mean, var,
                          beta1=self.beta1, beta2=self.beta2,
                          epsilon=self.epsilon, t=t,
                          bias_correction=self.bias_correction, wd=wd,
                          rescale_grad=self.rescale_grad,
                          clip_gradient=_clip(self.clip_gradient))
        invoke("lamb_update_phase2", weight, g_update, lr=lr,
               lower_bound=_clip(self.lower_bound),
               upper_bound=_clip(self.upper_bound))


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling (reference: optimizer.LARS)."""

    def __init__(self, learning_rate=0.1, momentum=0.0, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        w_norm = float(weight.norm().asscalar())
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g_norm = float(g.norm().asscalar())
        if w_norm > 0 and g_norm > 0:
            lars_ratio = self.eta * w_norm / \
                (g_norm + wd * w_norm + self.epsilon)
            lr = lr * lars_ratio
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=_clip(self.clip_gradient))
        if state is not None:
            invoke("sgd_mom_update", weight, grad, state,
                   momentum=self.momentum, **kw)
        else:
            invoke("sgd_update", weight, grad, **kw)


@register
class SignSGD(Optimizer):
    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        invoke("signsgd_update", weight, grad, lr=self._get_lr(index),
               wd=self._get_wd(index), rescale_grad=self.rescale_grad,
               clip_gradient=_clip(self.clip_gradient))


@register
class Signum(Optimizer):
    """Reference: optimizer.Signum → signum_update."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if state is not None:
            invoke("signum_update", weight, grad, state, lr=lr, wd=wd,
                   momentum=self.momentum, wd_lh=self.wd_lh,
                   rescale_grad=self.rescale_grad,
                   clip_gradient=_clip(self.clip_gradient))
        else:
            invoke("signsgd_update", weight, grad, lr=lr, wd=wd,
                   rescale_grad=self.rescale_grad,
                   clip_gradient=_clip(self.clip_gradient))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.DCASGD)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (grad + wd * weight + self.lamda *
                       grad * grad * (weight - previous_weight))
        if mom is not None:
            mom[:] = self.momentum * mom + delta
            delta = mom
        previous_weight[:] = weight
        weight += delta


@register
class Test(Optimizer):
    """Reference: optimizer.Test — used by test_optimizer comparisons."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


def _updater_census_arrays(u):
    """One updater's live slot-state device buffers for the census."""
    import jax as _jax
    out = []
    for st in u.states.values():
        for leaf in _jax.tree_util.tree_leaves(st):
            a = getattr(leaf, "_jax", leaf)
            if hasattr(a, "nbytes"):
                out.append(a)
    return out


class Updater:
    """Apply an optimizer to (index, grad, weight) triples — the kvstore
    server-side hook (reference: get_updater / class Updater).

    Called with LISTS (Trainer._update, Module.update and KVStore.push all
    batch their params into one call), an aggregate-enabled optimizer
    applies the whole group as one fused pytree dispatch instead of N —
    the reference's multi_sgd_update path, finally wired up (the old
    ``aggregate_updates`` flag was computed and then ignored)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}
        # buffer-census attribution (ISSUE 10): slot state (momenta,
        # adam moments, fp32 masters) lands in "optimizer_state"
        from .. import programs as _programs
        _programs.track_buffers("optimizer_state", self,
                                _updater_census_arrays)

    @property
    def aggregate_updates(self):
        return self.optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            index = [index]
            grad = [grad]
            weight = [weight]
        # per-device update counts (reference: Updater.__call__ →
        # _set_current_context): the Trainer runs one Updater per device
        # over the SAME optimizer object, so without switching the count
        # table each device copy would advance num_update — Adam-family
        # bias correction then sees t jump by #devices per step AND
        # differ across copies, silently desynchronizing the replicas
        ctx = getattr(weight[0], "context", None)
        if ctx is not None:
            self.optimizer._set_current_context(
                (ctx.canonical_type, ctx.device_id))
        for i, w in zip(index, weight):
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
        todo = list(zip(index, grad, weight))
        if self.aggregate_updates and len(todo) > 1:
            # sparse grads/weights are excluded from fusion: their update
            # is a per-key gather/scatter keyed on nnz, not a dense pytree
            fusable = [(i, g, w) for i, g, w in todo
                       if getattr(g, "stype", "default") == "default"
                       and getattr(w, "stype", "default") == "default"]
            if len(fusable) > 1 and self.optimizer.fused_update(
                    [i for i, _, _ in fusable],
                    [w for _, _, w in fusable],
                    [g for _, g, _ in fusable],
                    [self.states[i] for i, _, _ in fusable]):
                fused = {i for i, _, _ in fusable}
                todo = [t for t in todo if t[0] not in fused]
        for i, g, w in todo:
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def get_states(self, dump_optimizer=False):
        if dump_optimizer:
            return pickle.dumps((self.states, self.optimizer))
        return pickle.dumps(self.states)

    def set_states(self, states):
        loaded = pickle.loads(states)
        if isinstance(loaded, tuple) and len(loaded) == 2 and \
                isinstance(loaded[1], Optimizer):
            self.states, self.optimizer = loaded
        else:
            self.states = loaded
        self.states_synced = dict.fromkeys(self.states.keys(), False)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (reference: optimizer.FTML →
    ftml_update fused kernel)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        return (nd.zeros_like(z), nd.zeros_like(z), z)   # d, v, z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        d, v, z = state
        invoke("ftml_update", weight, grad, d, v, z,
               lr=self._get_lr(index), beta1=self.beta1, beta2=self.beta2,
               epsilon=self.epsilon, t=t, wd=self._get_wd(index),
               rescale_grad=self.rescale_grad,
               clip_grad=_clip(self.clip_gradient))


@register
class Adamax(Optimizer):
    """Infinity-norm Adam variant (reference: optimizer.Adamax — python
    update over nd ops, no fused kernel in the reference either)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = invoke("clip", grad, a_min=-self.clip_gradient,
                          a_max=self.clip_gradient)
        m, u = state
        m[:] = self.beta1 * m + (1.0 - self.beta1) * grad
        u[:] = invoke("maximum", self.beta2 * u, invoke("abs", grad))
        weight[:] = weight - lr * m / (u + 1e-8)


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: optimizer.Nadam — momentum-schedule
    python update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = invoke("clip", grad, a_min=-self.clip_gradient,
                          a_max=self.clip_gradient)
        mu_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_tp1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1)
                                                    * self.schedule_decay))
        self.m_schedule = self.m_schedule * mu_t
        m_schedule_next = self.m_schedule * mu_tp1
        m, v = state
        m[:] = self.beta1 * m + (1.0 - self.beta1) * grad
        v[:] = self.beta2 * v + (1.0 - self.beta2) * grad * grad
        g_prime = grad / (1.0 - self.m_schedule)
        m_prime = m / (1.0 - m_schedule_next)
        v_prime = v / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - mu_t) * g_prime + mu_tp1 * m_prime
        weight[:] = weight - lr * m_bar / (invoke("sqrt", v_prime)
                                           + self.epsilon)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference: optimizer.SGLD
    — posterior sampling: half-lr gradient step + sqrt(lr) gaussian
    noise)."""

    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        # reference order: clip the RESCALED gradient, then add the full
        # (unclipped) weight-decay force
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = invoke("clip", grad, a_min=-self.clip_gradient,
                          a_max=self.clip_gradient)
        grad = grad + wd * weight
        noise = nd.random.normal(0.0, math.sqrt(lr), shape=weight.shape,
                                 ctx=weight.context)
        weight[:] = weight - lr / 2.0 * grad + noise
