"""Global RNG seeding (reference: python/mxnet/random.py `def seed`,
src/resource.cc per-device SeedRandom).

On TPU seeding replaces the process-global root PRNG key; per-ctx seeds
(`mx.random.seed(s, ctx=...)`) collapse to the same key because the stateless
counter-based design already gives device-independent streams.
"""
from __future__ import annotations

from .ops import random as _impl

__all__ = ["seed"]


def seed(seed_state: int, ctx=None) -> None:
    _impl.seed(seed_state)
