"""Device contexts (reference: python/mxnet/context.py `class Context`,
include/mxnet/base.h `Context::GPU/CPU`).

TPU-native mapping: a Context names a jax.Device. `mx.tpu(i)` is the
first-class accelerator context (the reference's `mx.gpu(i)` role); `mx.gpu(i)`
is kept as a compatibility alias for the accelerator so reference scripts run
unmodified. `mx.cpu()` maps to the host XLA:CPU backend. When no TPU backend
is present (pure-CPU test environments with a forced 8-device host platform),
`tpu(i)` resolves to the i-th CPU device so the full test suite exercises
multi-device logic on a fake mesh (SURVEY.md §4.5).
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "num_gpus", "num_tpus",
           "tpu_memory_info", "gpu_memory_info",
           "current_context", "current_device", "Device"]

_ACCEL_PLATFORMS = ("tpu", "axon")  # axon = tunneled TPU platform name


def _accel_devices() -> List[jax.Device]:
    """Device ids are PROCESS-LOCAL, like the reference's per-worker gpu(i):
    under jax.distributed, rank r's cpu(0)/tpu(0) must resolve to one of
    r's own (addressable) devices, never another process's — hence
    jax.local_devices, not jax.devices."""
    from .base import get_env
    if get_env("MX_FORCE_CPU", dtype=bool):
        # test harness: pretend no accelerator so tpu(i) maps onto the fake
        # 8-device host mesh (SURVEY.md §4.5)
        return []
    for plat in _ACCEL_PLATFORMS:
        try:
            devs = jax.local_devices(backend=plat)
            if devs:
                return devs
        except RuntimeError:
            continue
    return []


def _cpu_devices() -> List[jax.Device]:
    try:
        return jax.local_devices(backend="cpu")
    except RuntimeError:
        # No cpu backend registered (rare); fall back to default platform.
        return jax.local_devices()


class Context:
    """A device context. devtype in {'cpu', 'tpu', 'gpu', 'cpu_pinned'}.

    'gpu' is an alias for the accelerator (tpu); 'cpu_pinned' aliases cpu
    (PJRT manages pinned staging buffers itself).
    """

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 5}
    _default_ctx = threading.local()

    __slots__ = ("device_typeid", "device_id", "_old_ctx")

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        elif isinstance(device_type, str):
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        else:
            self.device_typeid = int(device_type)
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    # -- identity ----------------------------------------------------------
    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.canonical_type, self.device_id))

    @property
    def canonical_type(self) -> str:
        """'gpu' and 'tpu' are the same physical accelerator here."""
        t = self.device_type
        if t == "gpu":
            return "tpu"
        if t == "cpu_pinned":
            return "cpu"
        return t

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.canonical_type == other.canonical_type
                and self.device_id == other.device_id)

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    # -- jax mapping -------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        if self.canonical_type == "tpu":
            devs = _accel_devices()
            if not devs:  # fake-mesh fallback: tpu(i) -> i-th host device
                devs = _cpu_devices()
        else:
            devs = _cpu_devices()
        if self.device_id >= len(devs):
            raise ValueError(
                "%s: device_id %d out of range (%d %s device(s) visible)"
                % (self, self.device_id, len(devs), self.canonical_type))
        return devs[self.device_id]

    # -- default-context stack (reference: with mx.Context(...)) -----------
    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False

    def empty_cache(self):
        """Reference: Context.empty_cache. PJRT owns pooling; best-effort."""
        # jax has no public per-device cache drop; live buffers stay valid.
        return None


# Device is the 2.x-era name for Context.
Device = Context


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Compatibility alias: accelerator context (maps to the TPU chip)."""
    return Context("gpu", device_id)


def num_tpus() -> int:
    devs = _accel_devices()
    if devs:
        return len(devs)
    # fake-mesh fallback mirrors tpu()'s resolution
    return len(_cpu_devices())


def num_gpus() -> int:
    """Reference: mx.context.num_gpus — here the accelerator count."""
    return len(_accel_devices())


def current_context() -> Context:
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


current_device = current_context


def tpu_memory_info(device_id: int = 0):
    """(free, total) bytes on the accelerator (reference:
    mx.context.gpu_memory_info → MXGetGPUMemoryInformation64).

    Backed by the PJRT allocator's memory_stats; backends that expose no
    stats (CPU) report (0, 0) — the reference raises there, but a soft
    zero keeps monitoring loops portable across the fake-mesh tests.
    """
    ctx = Context("tpu", device_id)
    stats = ctx.jax_device.memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return (total - used, total)


def gpu_memory_info(device_id: int = 0):
    """Compatibility alias (reference name) for tpu_memory_info."""
    return tpu_memory_info(device_id)
