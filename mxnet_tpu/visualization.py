"""Network visualization: print_summary + plot_network.

Reference: python/mxnet/visualization.py (print_summary — the layer table
with shapes and parameter counts; plot_network — the graphviz Digraph).

plot_network emits DOT source directly (a tiny ``_Dot`` shim mirrors
graphviz.Digraph's API surface we need) so the subsystem has zero
dependencies; if the real ``graphviz`` package is importable the genuine
Digraph object is returned instead, exactly like the reference.
"""
from __future__ import annotations

import json
from typing import Dict, Optional

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


class _Dot:
    """Minimal graphviz.Digraph stand-in: collects nodes/edges, renders
    DOT text via .source; .render writes the .dot file."""

    def __init__(self, name="plot", **_kw):
        self.name = name
        self._lines = []

    def node(self, name, label=None, **attrs):
        a = dict(attrs)
        if label is not None:
            a["label"] = label
        s = ", ".join('%s="%s"' % (k, v) for k, v in sorted(a.items()))
        self._lines.append('  "%s" [%s];' % (name, s))

    def edge(self, tail, head, label=None, **attrs):
        a = dict(attrs)
        if label:
            a["label"] = label
        s = ", ".join('%s="%s"' % (k, v) for k, v in sorted(a.items()))
        self._lines.append('  "%s" -> "%s"%s;'
                           % (tail, head, " [%s]" % s if s else ""))

    @property
    def source(self):
        return "digraph %s {\n%s\n}\n" % (self.name, "\n".join(self._lines))

    def render(self, filename=None, **_kw):
        filename = filename or (self.name + ".dot")
        if not filename.endswith(".dot"):
            filename += ".dot"
        with open(filename, "w") as f:
            f.write(self.source)
        return filename


_FILLCOLORS = {
    "FullyConnected": "#fb8072", "Convolution": "#fb8072",
    "Deconvolution": "#fb8072", "Activation": "#ffffb3",
    "LeakyReLU": "#ffffb3", "BatchNorm": "#bebada",
    "LayerNorm": "#bebada", "Pooling": "#80b1d3", "concat": "#fdb462",
    "softmax": "#fccde5", "SoftmaxOutput": "#fccde5",
}


def _node_label(node) -> str:
    op = node.op
    attrs = node.attrs or {}
    if op == "FullyConnected":
        return "FullyConnected\n%s" % attrs.get("num_hidden", "")
    if op in ("Convolution", "Deconvolution"):
        return "%s\n%sx%s/%s, %s" % (op, *_kern(attrs))
    if op == "Activation" or op == "LeakyReLU":
        return "%s\n%s" % (op, attrs.get("act_type", ""))
    if op == "Pooling":
        return "Pooling\n%s, %sx%s/%s" % ((attrs.get("pool_type", "max"),)
                                          + _kern(attrs)[:3])
    return op


def _kern(attrs):
    import ast

    def twos(v, d="1"):
        # literal_eval only: attrs may come from an UNTRUSTED symbol.json
        try:
            t = ast.literal_eval(str(v)) if v else (int(d), int(d))
        except (ValueError, SyntaxError):
            t = (d, d)
        t = t if isinstance(t, tuple) else (t, t)
        return t
    k = twos(attrs.get("kernel"), "1")
    s = twos(attrs.get("stride"), "1")
    return (str(k[0]), str(k[1]), str(s[0]), attrs.get("num_filter", ""))


def _walk(symbol):
    """Topo-ordered unique nodes of a Symbol DAG."""
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child, _ in node.inputs:
            visit(child)
        order.append(node)
    for node, _ in symbol._heads:
        visit(node)
    return order


def print_summary(symbol, shape: Optional[Dict] = None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Layer-table summary (reference: visualization.print_summary).
    Returns the table string (and prints it)."""
    shapes = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        shapes = dict(zip(symbol.list_arguments(), arg_shapes))
    nodes = _walk(symbol)
    pos = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    lines = ["_" * line_length]
    row = ""
    for i, h in enumerate(header):
        row += h + " " * max(1, pos[i] - len(row) - len(h))
    lines += [row, "=" * line_length]
    total_params = 0
    for node in nodes:
        if node.op == "null":
            continue
        params = 0
        for child, _ in node.inputs:
            if child.op == "null" and child.name in shapes:
                n = 1
                for d in shapes[child.name]:
                    n *= d
                if not child.name.endswith(("data", "label")):
                    params += n
        total_params += params
        prevs = ",".join(c.name for c, _ in node.inputs if c.op != "null")
        cells = ["%s (%s)" % (node.name, node.op), "", str(params), prevs]
        row = ""
        for i, c in enumerate(cells):
            row += c + " " * max(1, pos[i] - len(row) - len(c))
        lines.append(row)
    lines += ["=" * line_length, "Total params: %d" % total_params,
              "_" * line_length]
    table = "\n".join(lines)
    print(table)
    return table


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """DOT graph of the symbol DAG (reference: plot_network).  Returns a
    graphviz.Digraph when the package is available, else the built-in shim
    (same .source / .render surface)."""
    try:
        from graphviz import Digraph  # optional, like the reference
        dot = Digraph(name=title, format=save_format)
    except ImportError:
        dot = _Dot(name=title)
    base_attrs = {"shape": "box", "fixedsize": "false", "style": "filled"}
    base_attrs.update(node_attrs or {})
    names = set()
    for node in _walk(symbol):
        if node.op == "null":
            is_weight = node.name.endswith(("_weight", "_bias", "_gamma",
                                            "_beta", "_moving_mean",
                                            "_moving_var"))
            if hide_weights and is_weight:
                continue
            dot.node(node.name, label=node.name, fillcolor="#8dd3c7",
                     **base_attrs)
        else:
            dot.node(node.name, label=_node_label(node),
                     fillcolor=_FILLCOLORS.get(node.op, "#b3de69"),
                     **base_attrs)
        names.add(node.name)
    for node in _walk(symbol):
        if node.op == "null":
            continue
        for child, _ in node.inputs:
            if child.name in names:
                dot.edge(child.name, node.name)
    return dot
