"""mx.npx — numpy-mode operator extensions (2.x era).

Reference: ``python/mxnet/ndarray/numpy_extension/_op.py`` + the
``mxnet.npx`` namespace (set_np/reset_np, activation/layer ops, data ops).

``set_np()`` in the reference flips global array-semantics switches; this
rebuild has numpy semantics natively (one array type over jax), so the
switches only record intent for code that asserts on them.
"""
from __future__ import annotations

from .ndarray.ndarray import invoke, NDArray

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "use_np", "use_np_array", "use_np_shape"]

_np_array = False
_np_shape = False


def set_np(shape=True, array=True, dtype=None):
    """Reference: npx.set_np — enable numpy semantics (native here)."""
    global _np_array, _np_shape
    _np_array = array
    _np_shape = shape


def reset_np():
    set_np(shape=False, array=False)


def is_np_array() -> bool:
    return _np_array


def is_np_shape() -> bool:
    return _np_shape


def use_np(func_or_cls):
    """Decorator form (reference: npx.use_np) — a no-op marker here."""
    return func_or_cls


use_np_array = use_np
use_np_shape = use_np


def _op(op_name, pyname=None):
    def f(*args, **kwargs):
        return invoke(op_name, *args, **kwargs)
    f.__name__ = pyname or op_name.lstrip("_").lower()
    f.__doc__ = "npx.%s — registry op %r" % (f.__name__, op_name)
    return f


# activation / nn ops (reference: npx.activation, npx.softmax, ...)
activation = _op("Activation", "activation")
relu = _op("relu")
sigmoid = _op("sigmoid")
log_sigmoid = _op("log_sigmoid")
softmax = _op("softmax")
log_softmax = _op("log_softmax")
masked_softmax = _op("masked_softmax")
masked_log_softmax = _op("masked_log_softmax")
leaky_relu = _op("LeakyReLU", "leaky_relu")
gelu = _op("gelu")
batch_norm = _op("BatchNorm", "batch_norm")
layer_norm = _op("LayerNorm", "layer_norm")
group_norm = _op("GroupNorm", "group_norm")
instance_norm = _op("InstanceNorm", "instance_norm")
l2_normalization = _op("L2Normalization", "l2_normalization")
convolution = _op("Convolution", "convolution")
deconvolution = _op("Deconvolution", "deconvolution")
pooling = _op("Pooling", "pooling")
fully_connected = _op("FullyConnected", "fully_connected")
embedding = _op("Embedding", "embedding")
dropout = _op("Dropout", "dropout")
rnn = _op("RNN", "rnn")
multi_head_attention = _op("multi_head_attention")
ctc_loss = _op("CTCLoss", "ctc_loss")
smooth_l1 = _op("smooth_l1")
# data / indexing ops
topk = _op("topk")
pick = _op("pick")
one_hot = _op("one_hot")
gather_nd = _op("gather_nd")
scatter_nd = _op("scatter_nd")
batch_dot = _op("batch_dot")
sequence_mask = _op("sequence_mask")
shape_array = _op("shape_array")
boolean_mask = _op("boolean_mask")
# casting / misc
cast = _op("Cast", "cast")
amp_cast = _op("amp_cast")


def load(fname):
    """npx.load — dict of arrays (reference: npx.load)."""
    from . import ndarray as nd
    return nd.load(fname)


def save(fname, data):
    from . import ndarray as nd
    return nd.save(fname, data)


def waitall():
    from .ndarray import waitall as _w
    _w()


# -- npx.special: XLA-lowered special functions (beyond-reference TPU
# primitives; jax.scipy.special via the registry so they ride the per-op
# jit cache and autograd tape) -------------------------------------------
import sys as _sys
from types import ModuleType as _ModuleType

special = _ModuleType(__name__ + ".special")
for _sname in ("betainc", "zeta", "ndtr", "ndtri", "log_ndtr", "logit",
               "expit", "xlogy", "xlog1py", "entr", "rel_entr", "kl_div",
               "i0e", "i1", "i1e",
               # second batch: registered defensively per jax build —
               # only expose what the registry actually has
               "betaln", "expi", "expn", "exp1", "factorial",
               "gammasgn", "hyp1f1", "poch", "spence"):
    from .ops.registry import _REGISTRY as _regtab
    if "_npx_" + _sname not in _regtab:
        continue
    def _mk_special(_opn="_npx_" + _sname):
        def f(*args):
            return invoke(_opn, *args)
        return f
    setattr(special, _sname, _mk_special())
    getattr(special, _sname).__name__ = _sname
if "_npx_multigammaln" in _regtab:
    def _multigammaln(a, d):
        return invoke("_npx_multigammaln", a, d=int(d))
    _multigammaln.__name__ = "multigammaln"
    special.multigammaln = _multigammaln
if "_npx_bernoulli" in _regtab:
    def _bernoulli(n):
        return invoke("_npx_bernoulli", n=int(n))
    _bernoulli.__name__ = "bernoulli"
    special.bernoulli = _bernoulli
_sys.modules[special.__name__] = special


# -- npx.stats: distribution densities over the registry ------------------
stats = _ModuleType(__name__ + ".stats")
for _dist, _fns in (("norm", ("pdf", "logpdf", "cdf", "logcdf")),
                    ("expon", ("logpdf",)), ("gamma", ("logpdf",)),
                    ("beta", ("logpdf",)), ("t", ("logpdf",)),
                    ("cauchy", ("logpdf",)), ("laplace", ("logpdf",)),
                    ("uniform", ("logpdf",)),
                    ("poisson", ("pmf", "logpmf")),
                    ("bernoulli", ("logpmf",))):
    _dm = _ModuleType(stats.__name__ + "." + _dist)
    for _f in _fns:
        def _mk_stat(_opn="_npx_stats_%s_%s" % (_dist, _f)):
            def g(*args):
                return invoke(_opn, *args)
            return g
        setattr(_dm, _f, _mk_stat())
    setattr(stats, _dist, _dm)
    _sys.modules[_dm.__name__] = _dm
_sys.modules[stats.__name__] = stats


# -- 2.x npx surface stragglers ------------------------------------------
def gamma(x):
    """npx.gamma — the Gamma function (reference npx surface)."""
    return invoke("gamma", x)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    return invoke("arange_like", data, start=start, step=step,
                  repeat=repeat, axis=axis)


def broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    return invoke("broadcast_like", lhs, rhs,
                  lhs_axes=tuple(lhs_axes) if lhs_axes is not None
                  else None,
                  rhs_axes=tuple(rhs_axes) if rhs_axes is not None
                  else None)


def reshape_like(lhs, rhs):
    return invoke("reshape_like", lhs, rhs)


def cpu(device_id=0):
    from .device import cpu as _cpu
    return _cpu(device_id)


def gpu(device_id=0):
    from .device import gpu as _gpu
    return _gpu(device_id)


def tpu(device_id=0):
    from .device import tpu as _tpu
    return _tpu(device_id)


def num_gpus():
    from .device import num_gpus as _n
    return _n()


def current_device():
    from .device import current_context as _cc
    return _cc()
