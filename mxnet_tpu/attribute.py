"""mx.attribute (reference: python/mxnet/attribute.py) — AttrScope's
canonical home; the implementation lives with the symbol DAG."""
from .symbol import AttrScope

__all__ = ["AttrScope"]
