"""mx.recordio — the .rec/.idx container.

Reference: ``python/mxnet/recordio.py`` (MXRecordIO, MXIndexedRecordIO,
IRHeader, pack/unpack/pack_img/unpack_img) over
``3rdparty/dmlc-core/include/dmlc/recordio.h``.

The parsing core is native C++ (``src/recordio.cc``, loaded via ctypes) —
byte-compatible with reference-written .rec files, including multi-chunk
records (payloads embedding the magic).  A pure-Python reader/writer backs
it up when no compiler is available (same format, slower).

Image payloads (pack_img/unpack_img) use PIL for JPEG/PNG codec work — the
role the reference fills with OpenCV.
"""
from __future__ import annotations

import ctypes
import io as _io
import numbers
import os
import struct
import threading
import warnings
from collections import namedtuple

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a


def _native():
    try:
        from . import _native as nat
        lib = nat.load("recordio")
    except OSError:
        return None
    lib.MXRecordIOWriterCreate.restype = ctypes.c_void_p
    lib.MXRecordIOWriterCreate.argtypes = [ctypes.c_char_p]
    lib.MXRecordIOWriterWrite.restype = ctypes.c_int64
    lib.MXRecordIOWriterWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_uint64]
    lib.MXRecordIOWriterTell.restype = ctypes.c_int64
    lib.MXRecordIOWriterTell.argtypes = [ctypes.c_void_p]
    lib.MXRecordIOWriterClose.argtypes = [ctypes.c_void_p]
    lib.MXRecordIOReaderCreate.restype = ctypes.c_void_p
    lib.MXRecordIOReaderCreate.argtypes = [ctypes.c_char_p]
    lib.MXRecordIOReaderNext.restype = ctypes.c_int
    lib.MXRecordIOReaderNext.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_char_p),
                                         ctypes.POINTER(ctypes.c_uint64)]
    lib.MXRecordIOReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.MXRecordIOReaderTell.restype = ctypes.c_int64
    lib.MXRecordIOReaderTell.argtypes = [ctypes.c_void_p]
    lib.MXRecordIOReaderClose.argtypes = [ctypes.c_void_p]
    return lib


_LIB = None
_LIB_TRIED = False


def _get_lib():
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB = _native()
        _LIB_TRIED = True
    return _LIB


class MXRecordIO:
    """Sequential .rec reader/writer (reference: MXRecordIO)."""

    def __init__(self, uri: str, flag: str):
        if flag not in ("r", "w"):
            raise ValueError("flag must be 'r' or 'w'")
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self._handle = None
        self._lib = None      # pinned per instance so close() survives
        self._pyfile = None   # python fallback
        self._read_lock = threading.Lock()
        self.corrupt_skipped = 0   # records dropped under tolerate mode
        self._corrupt_eof = False  # tolerated damage: reads report EOF
        self.open()

    # -- lifecycle ----------------------------------------------------------
    def open(self):
        self._lib = _get_lib()
        if self._lib is not None:
            if self.flag == "w":
                self._handle = self._lib.MXRecordIOWriterCreate(
                    self.uri.encode())
            else:
                self._handle = self._lib.MXRecordIOReaderCreate(
                    self.uri.encode())
            if not self._handle:
                raise OSError("cannot open %r" % self.uri)
        else:
            self._pyfile = open(self.uri, "wb" if self.flag == "w" else "rb")
        self._corrupt_eof = False     # reset()/reopen clears the latch
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        if self._handle is not None and self._lib is not None:
            if self.flag == "w":
                self._lib.MXRecordIOWriterClose(self._handle)
            else:
                self._lib.MXRecordIOReaderClose(self._handle)
            self._handle = None
        if self._pyfile is not None:
            self._pyfile.close()
            self._pyfile = None
        self.is_open = False

    def reset(self):
        """Reopen at the beginning (reference: MXRecordIO.reset)."""
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass  # interpreter shutdown: module globals may be gone

    def __getstate__(self):
        """Readers are picklable for multiprocess DataLoader workers —
        the handle is dropped and each process reopens on unpickle
        (reference: recordio reopening across _MultiWorkerIter forks).
        Writers hold buffered state and must not cross processes."""
        if self.flag != "r":
            raise RuntimeError("MXRecordIO writers are not picklable")
        state = self.__dict__.copy()
        state["_handle"] = None
        state["_lib"] = None
        state["_pyfile"] = None
        state["is_open"] = False
        state.pop("_read_lock", None)     # locks do not pickle
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._read_lock = threading.Lock()
        self.open()

    # -- IO ------------------------------------------------------------------
    def write(self, buf: bytes) -> None:
        assert self.flag == "w"
        if self._handle is not None:
            pos = self._lib.MXRecordIOWriterWrite(self._handle, buf,
                                                  len(buf))
            if pos < 0:
                raise OSError("recordio write failed")
            self._last_pos = pos
        else:
            self._last_pos = self._py_write(buf)

    def read(self):
        """Next record payload as bytes, or None at EOF."""
        assert self.flag == "r"
        if self._corrupt_eof:
            # a tolerated corruption ended this pass: stay EOF (and keep
            # the count stable) instead of re-detecting the same damage
            # on every subsequent call — reset() clears the latch
            return None
        if self._handle is not None:
            data = ctypes.c_char_p()
            size = ctypes.c_uint64()
            rc = self._lib.MXRecordIOReaderNext(
                self._handle, ctypes.byref(data), ctypes.byref(size))
            if rc == 1:
                return None
            if rc != 0:
                return self._corrupt_record(
                    self._lib.MXRecordIOReaderTell(self._handle),
                    "corrupt record")
            return ctypes.string_at(data, size.value)
        return self._py_read()

    def _corrupt_record(self, offset: int, why: str):
        """Corruption policy, shared by both reader backends.

        The classic damage is a tail record cut short by a mid-write
        crash; default is a loud OSError naming the uri and byte offset
        so the operator knows exactly what to truncate or re-pack.
        With ``MX_RECORDIO_TOLERATE_CORRUPT=1`` the damaged record is
        skipped-and-counted (``self.corrupt_skipped``) and the read
        reports EOF — resuming a job over the damaged file keeps every
        intact record before the tear."""
        from .base import get_env
        if get_env("MX_RECORDIO_TOLERATE_CORRUPT", dtype=bool):
            self.corrupt_skipped += 1
            warnings.warn(
                "recordio: skipping %s in %r at byte offset %d "
                "(MX_RECORDIO_TOLERATE_CORRUPT=1; %d skipped so far)"
                % (why, self.uri, offset, self.corrupt_skipped))
            self._corrupt_eof = True         # damaged tail: stop here
            if self._pyfile is not None:
                self._pyfile.seek(0, 2)
            return None
        raise OSError(
            "%s in recordio file %r at byte offset %d (set "
            "MX_RECORDIO_TOLERATE_CORRUPT=1 to skip damaged records, "
            "e.g. a tail torn by a mid-write crash)"
            % (why, self.uri, offset))

    def tell(self) -> int:
        if self._handle is not None:
            if self.flag == "w":
                return self._lib.MXRecordIOWriterTell(self._handle)
            return self._lib.MXRecordIOReaderTell(self._handle)
        return self._pyfile.tell()

    # -- pure-python fallback (same wire format) -----------------------------
    def _py_write(self, buf: bytes) -> int:
        f = self._pyfile
        pos = f.tell()
        magic_bytes = struct.pack("<I", _MAGIC)
        # split on embedded magics like the native writer
        chunks = []
        start = 0
        while True:
            hit = buf.find(magic_bytes, start)
            if hit < 0:
                chunks.append(buf[start:])
                break
            chunks.append(buf[start:hit])
            start = hit + 4
        for i, chunk in enumerate(chunks):
            if len(chunks) == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == len(chunks) - 1:
                cflag = 3
            else:
                cflag = 2
            lrec = (cflag << 29) | len(chunk)
            f.write(magic_bytes)
            f.write(struct.pack("<I", lrec))
            f.write(chunk)
            pad = (4 - (len(chunk) & 3)) & 3
            f.write(b"\x00" * pad)
        return pos

    def _py_read(self):
        f = self._pyfile
        start = f.tell()             # record start: reported on damage
        out = []
        in_multi = False
        while True:
            head = f.read(4)
            if not head and not in_multi:
                return None          # clean EOF on a record boundary
            if len(head) != 4:
                return self._corrupt_record(
                    start, "truncated record header (mid-write tear)")
            if struct.unpack("<I", head)[0] != _MAGIC:
                return self._corrupt_record(
                    start, "corrupt record header (bad magic)")
            lenb = f.read(4)
            if len(lenb) != 4:
                return self._corrupt_record(
                    start, "truncated record length field")
            lrec = struct.unpack("<I", lenb)[0]
            cflag, clen = lrec >> 29, lrec & ((1 << 29) - 1)
            if in_multi:
                out.append(struct.pack("<I", _MAGIC))
            data = f.read(clen)
            if len(data) != clen:
                return self._corrupt_record(
                    start, "truncated record payload (%d of %d bytes)"
                    % (len(data), clen))
            f.read((4 - (clen & 3)) & 3)
            out.append(data)
            if cflag in (0, 3):
                return b"".join(out)
            in_multi = True


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec via a .idx sidecar of ``key\\toffset`` lines
    (reference: MXIndexedRecordIO — what ImageRecordIter seeks with)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.flag == "w" and self.is_open:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write("%s\t%d\n" % (key, self.idx[key]))
        super().close()

    def seek(self, idx):
        assert self.flag == "r"
        # the corrupt-EOF latch is a sequential-pass concept; a seek
        # repositions the stream, so one tolerated bad record must not
        # swallow every other (intact) record of a random-access pass
        self._corrupt_eof = False
        pos = self.idx[idx]
        if self._handle is not None:
            self._lib.MXRecordIOReaderSeek(self._handle, pos)
        else:
            self._pyfile.seek(pos)

    def read_idx(self, idx):
        # seek+read must be atomic: DataLoader's thread_pool path (and any
        # user threads) share one reader, and an interleaved seek silently
        # returns the WRONG record
        with self._read_lock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf: bytes):
        assert self.flag == "w"
        self.write(buf)
        self.idx[idx] = self._last_pos
        self.keys.append(idx)


# -- IRHeader + pack/unpack ---------------------------------------------------

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Serialize header+payload (reference: recordio.pack).  ``flag`` > 0
    means the label is a vector of ``flag`` floats prepended to the
    payload."""
    label = header.label
    if not isinstance(label, numbers.Number):
        label = _np.asarray(label, dtype=_np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                       header.id2) + s


def unpack(s: bytes):
    """Inverse of pack → (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[:header.flag * 4], dtype=_np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality: int = 95,
             img_fmt: str = ".jpg") -> bytes:
    """Encode an HWC uint8 image (numpy or NDArray) into a packed record
    (reference: recordio.pack_img; PIL plays OpenCV's role)."""
    from PIL import Image
    if hasattr(img, "asnumpy"):
        img = img.asnumpy()
    img = _np.asarray(img, dtype=_np.uint8)
    pil = Image.fromarray(img)
    buf = _io.BytesIO()
    fmt = img_fmt.lstrip(".").upper()
    if fmt in ("JPG", "JPEG"):
        pil.save(buf, format="JPEG", quality=quality)
    elif fmt == "PNG":
        pil.save(buf, format="PNG")
    else:
        raise ValueError("unsupported img_fmt %r" % img_fmt)
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=1):
    """Inverse of pack_img → (IRHeader, HWC uint8 ndarray)."""
    from PIL import Image
    header, payload = unpack(s)
    pil = Image.open(_io.BytesIO(payload))
    if iscolor == 0:
        pil = pil.convert("L")
    elif pil.mode != "RGB":
        pil = pil.convert("RGB")
    return header, _np.asarray(pil)
