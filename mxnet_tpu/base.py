"""Foundation utilities: env-flag system, registry helpers, error types.

TPU-native rebuild of the roles played by the reference's dmlc-core
(`dmlc/parameter.h` DMLC_DECLARE_PARAMETER reflection, `dmlc::GetEnv` flag
reads, `dmlc/logging.h` CHECK macros) and `python/mxnet/base.py` (ctypes
plumbing).  There is no C ABI here: the framework is Python-first over
jax/jaxlib, so "handle plumbing" reduces to ordinary Python objects.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "MXNetError",
    "get_env",
    "set_env",
    "environment",
    "string_types",
    "numeric_types",
    "integer_types",
]


class MXNetError(RuntimeError):
    """Default error type raised by the framework (reference: MXGetLastError)."""


string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)

# ---------------------------------------------------------------------------
# Env-flag system (reference: dmlc::GetEnv + env_var.md catalog).
# Flags are read lazily at first use, like the reference, but we also keep a
# process-local override dict so `mx.util.set_env` / the `environment()` test
# context-manager work without mutating os.environ for spawned workers.
# ---------------------------------------------------------------------------

_env_overrides: Dict[str, Optional[str]] = {}
_env_lock = threading.Lock()

# Canonical flag catalog: name -> (default, docstring). Kept for doc-gen and
# `mx.runtime` feature reporting; unknown MXNET_* flags still read through.
ENV_CATALOG: Dict[str, Any] = {
    "MXNET_ENGINE_TYPE": ("ThreadedEnginePerDevice", "Execution mode: 'NaiveEngine' forces synchronous per-op execution (block_until_ready after every op) for debugging; any other value keeps XLA async dispatch."),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": ("1", "No-op on TPU (XLA fuses); accepted for compat."),
    "MXNET_EXEC_BULK_EXEC_TRAIN": ("1", "No-op on TPU (XLA fuses); accepted for compat."),
    "MXNET_GPU_MEM_POOL_TYPE": ("Round", "No-op: PJRT owns HBM pooling."),
    "MXNET_KVSTORE_BIGARRAY_BOUND": ("1000000", "Gradient bucket size threshold for kvstore collectives."),
    "MXNET_ENFORCE_DETERMINISM": ("0", "Force deterministic kernels."),
    "MXNET_SAFE_ACCUMULATION": ("1", "Accumulate reductions in fp32 even for fp16/bf16 inputs."),
    "MXNET_DEFAULT_DTYPE": ("float32", "Default dtype for array creation."),
}


def get_env(name: str, default: Any = None, dtype: Callable = str) -> Any:
    """Read an env flag with overrides (reference: dmlc::GetEnv)."""
    with _env_lock:
        if name in _env_overrides:
            val = _env_overrides[name]
        else:
            val = os.environ.get(name)
    if val is None:
        if default is None and name in ENV_CATALOG:
            default = ENV_CATALOG[name][0]
        if default is None:
            return None
        val = default
    try:
        if dtype is bool:
            return str(val).lower() in ("1", "true", "yes", "on")
        return dtype(val)
    except (TypeError, ValueError):
        return default


def set_env(name: str, value: Optional[str]) -> None:
    """Set (or with None, unset) a process-local env override."""
    with _env_lock:
        _env_overrides[name] = None if value is None else str(value)
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = str(value)


class environment:
    """Context manager scoping env-var changes (reference:
    python/mxnet/test_utils.py (environment))."""

    def __init__(self, *args):
        if len(args) == 1 and isinstance(args[0], dict):
            self._kwargs = dict(args[0])
        elif len(args) == 2:
            self._kwargs = {args[0]: args[1]}
        else:
            raise ValueError("environment() takes (name, value) or a dict")
        self._saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        for k, v in self._kwargs.items():
            self._saved[k] = os.environ.get(k)
            set_env(k, v)
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            set_env(k, v)
        return False
