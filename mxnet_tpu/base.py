"""Foundation utilities: env-flag system, registry helpers, error types.

TPU-native rebuild of the roles played by the reference's dmlc-core
(`dmlc/parameter.h` DMLC_DECLARE_PARAMETER reflection, `dmlc::GetEnv` flag
reads, `dmlc/logging.h` CHECK macros) and `python/mxnet/base.py` (ctypes
plumbing).  There is no C ABI here: the framework is Python-first over
jax/jaxlib, so "handle plumbing" reduces to ordinary Python objects.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = [
    "MXNetError",
    "get_env",
    "set_env",
    "environment",
    "string_types",
    "numeric_types",
    "integer_types",
]


class MXNetError(RuntimeError):
    """Default error type raised by the framework (reference: MXGetLastError)."""


string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)

# ---------------------------------------------------------------------------
# Env-flag system (reference: dmlc::GetEnv + env_var.md catalog).
# Flags are read lazily at first use, like the reference, but we also keep a
# process-local override dict so `mx.util.set_env` / the `environment()` test
# context-manager work without mutating os.environ for spawned workers.
# ---------------------------------------------------------------------------

_env_overrides: Dict[str, Optional[str]] = {}
_env_lock = threading.Lock()

# Canonical flag catalog: name -> (default, docstring). Kept for doc-gen and
# `mx.runtime` feature reporting; unknown MXNET_* flags still read through.
ENV_CATALOG: Dict[str, Any] = {
    "MXNET_ENGINE_TYPE": ("ThreadedEnginePerDevice", "Execution mode: 'NaiveEngine' forces synchronous per-op execution (block_until_ready after every op) for debugging; any other value keeps XLA async dispatch."),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": ("1", "No-op on TPU (XLA fuses); accepted for compat."),
    "MXNET_EXEC_BULK_EXEC_TRAIN": ("1", "No-op on TPU (XLA fuses); accepted for compat."),
    "MXNET_GPU_MEM_POOL_TYPE": ("Round", "No-op: PJRT owns HBM pooling."),
    "MXNET_KVSTORE_BIGARRAY_BOUND": ("1000000", "Gradient bucket size threshold for kvstore collectives."),
    "MXNET_ENFORCE_DETERMINISM": ("0", "Force deterministic kernels."),
    "MXNET_PROFILER_SYNC": ("0", "1 = the profiler blocks until each annotated range's device work completes before stamping its duration (accurate per-range timings at the cost of breaking dispatch overlap)."),
    "MXNET_SAFE_ACCUMULATION": ("1", "Accumulate reductions in fp32 even for fp16/bf16 inputs."),
    "MXNET_DEFAULT_DTYPE": ("float32", "Default dtype for array creation."),
    # rebuild-specific flags (SURVEY §5.6: env vars are the de-facto flag
    # system; this catalog is the canonical doc source — docs/ENV_VARS.md
    # is generated from it by tools/gen_env_docs.py)
    "MX_MODULE_JIT": ("1", "0 disables the whole-graph-jit fast paths (Module fused train step AND Executor inference) - debugging escape hatch back to per-op dispatch."),
    "MX_FORCE_CPU": ("0", "Pin the CPU backend: mx.tpu(i) resolves to host devices and nothing touches the accelerator tunnel (tests, data workers)."),
    "MX_TEST_CTX": ("", "'tpu' switches the pytest lane to the real chip as default context (conftest probes the tunnel first)."),
    "MX_DATA_DIR": ("", "Root of real-dataset drops (mnist/, ptb/): arms tests/test_real_data.py and the examples' real-data paths."),
    "MX_PRETRAINED_DIR": ("~/.mxnet/models", "Local weight store scanned by model_zoo get_model(..., pretrained=True)."),
    "MX_COORDINATOR": ("", "host:port of process 0 for jax.distributed (set by tools/launch.py)."),
    "MX_NUM_PROCESSES": ("", "Process-group size for jax.distributed (launcher-set)."),
    "MX_PROCESS_ID": ("", "This process's rank (launcher-set)."),
    "MX_INIT_TIMEOUT": ("", "Seconds to bound the jax.distributed coordinator handshake (fail-fast + retry instead of hanging)."),
    "MX_PS_ROOT": ("", "dist_async parameter-server address host:port (single server)."),
    "MX_PS_ROOTS": ("", "Comma-separated PS addresses; keys hash-shard across them (launch.py -s N)."),
    "MX_PS_PORT": ("9600", "Port a kvstore server process binds (DMLC_ROLE=server)."),
    "MX_PS_SNAPSHOT": ("", "Path where a kvstore server persists its store (atomic pickle) after mutations and on STOP; a server restarted with the same path resumes with no data loss."),
    "MX_PS_SNAPSHOT_EVERY": ("1", "Snapshot the server store every N mutating requests (1 = every PUSH/INIT; larger trades durability for throughput)."),
    "MX_KVSTORE_BUCKET_KB": ("4096", "Fusion-bucket capacity in KB for coalesced gradient exchange: a batched push/pull packs small dense keys into flat per-dtype buckets of about this size, so a ResNet-scale step does a few bucket collectives/RPCs instead of ~160 per-key ones; 0 disables bucketing.  The key->bucket layout is a pure function of the ordered (key, shape, dtype) set, so workers and the PS agree with no coordination; the dist_async retry layer replays whole buckets."),
    "MX_GRAD_COMPRESS": ("", "Default gradient-wire compression for Trainers constructed without explicit compression_params: 'int8' (per-block symmetric int8 + error feedback, ~3.9x fewer exchange bytes), '2bit' (reference +-threshold/0 levels + error feedback), or 'bf16' (pure cast, half the bytes).  Empty ships full-width floats.  Launch scripts flip it fleet-wide; per-Trainer compression_params always wins."),
    "MX_GRAD_COMPRESS_BLOCK": ("256", "Elements per int8 scale block for 'int8' gradient compression: each block of this many gradient elements shares one f32 scale (max|block|/127), so the wire payload is n + 4n/block bytes per n-element gradient.  Smaller blocks track outliers tighter at more scale overhead."),
    "MX_STEP_COMPILE": ("0", "1 = whole-program compiled train step: loss forward, backward, the bucketed (int8/2bit error-feedback quantized) gradient exchange, the fused multi-tensor optimizer apply and device-side metric accumulation trace into ONE donated jax.jit per step (mxnet_tpu/step.py CompiledStep; Module.fit picks it up automatically).  First call traces, a shape/dtype change retraces, lr/wd arrive as traced scalars so schedulers never recompile.  Eager remains the debug path; the PS/dist_async transport, unsupported optimizers, grad_req='add' and NaN-policy-armed runs fall back to the eager pipeline automatically."),
    "MX_STEP_SCAN": ("0", "N>1 = scan-window size for the compiled step lane's window consumers (mxnet_tpu.step.scan_window(): bench.py --eager, tools/dispatch_count.py --compiled, and any harness driving CompiledStep.run_window): N prefetched batches stay on device per host round-trip, the step body runs under one lax.scan, and the window costs 1-2 dispatches total (batch transfer + window launch) instead of N; gradient accumulation folds into the scanned body via run_window(accum=k).  Module.fit dispatches per batch regardless (its iterator/callback contract is per-batch).  0/1 = one dispatch per step."),
    "MX_MESH_AXES": ("", "Named mesh axes for the SpecLayout sharded training lane (mxnet_tpu/parallel/speclayout.py), as comma-separated name[=size] tokens, e.g. 'data,fsdp=2' or 'data,fsdp=2,tp=2'.  When set, CompiledStep/Trainer.make_compiled_step build the step as ONE donated SPMD jit over this mesh: the batch splits over data*fsdp, parameters + optimizer state live sheet-sharded (fsdp) / tensor-split (tp) so per-chip state bytes drop ~linearly with the fsdp axis, gradients reduce-scatter onto the parameter shards (int8-quantized per bucket under gradient compression, error-feedback residuals sharded per chip) and XLA all-gathers updated parameters just in time.  An unsized data axis infers -1 (all remaining devices); unsized model axes default to 2.  Empty keeps the replicated step.  Sharding NEVER changes results - only placement and communication."),
    "MX_FSDP": ("", "Size of the fsdp (ZeRO sheet-sharding) mesh axis for the SpecLayout lane.  Overrides the fsdp entry of MX_MESH_AXES; setting MX_FSDP=N alone implies MX_MESH_AXES='data,fsdp=N'.  Per-chip params+optimizer_state bytes in buffer_census() drop ~1/N (acceptance: within 15% of ideal at N=2 and N=4 in dryrun_multichip).  Empty/1 = no fsdp sharding."),
    "MX_EXCHANGE_OVERLAP": ("0", "1 = overlap-scheduled gradient exchange: the Trainer arms per-gradient readiness hooks and each fusion bucket's collective launches the moment backward finalizes the bucket's last member (reverse-parameter-order buckets, so late layers go out first), with results committed at the pre-update drain barrier.  Exchange results are identical to the serialized path (a grad rewritten after launch relaunches its unit at drain); 0 keeps the exchange serialized after backward."),
    "MX_OPTIMIZER_AGGREGATE": ("", "Fused multi-tensor optimizer apply: empty keeps each optimizer's default aggregate_num (SGD/NAG/Adam/AdamW fuse up to 64 params per dispatch by default), 0 opts out back to the per-param update loop, any other N caps how many (weight, grad, state) triples fuse into one jitted pytree dispatch."),
    "MX_KVSTORE_RETRY_DEADLINE": ("60", "dist_async client: total seconds to keep retrying a failed RPC (reconnect + replay) before raising a terminal MXNetError; also bounds the initial connect wait per server at startup (the launcher starts servers concurrently, so workers retry until each binds)."),
    "MX_KVSTORE_RETRY_BASE": ("0.05", "dist_async client: first backoff delay in seconds; doubles per attempt."),
    "MX_KVSTORE_RETRY_MAX": ("2.0", "dist_async client: backoff delay cap in seconds."),
    "MX_KVSTORE_RETRY_JITTER": ("0.2", "dist_async client: uniform jitter fraction added to each backoff delay (decorrelates worker retry storms)."),
    "MX_KVSTORE_RECV_TIMEOUT": ("", "Seconds a kvstore recv_msg may block mid-message before raising TimeoutError (empty = block forever; the dist_async client always bounds its RPCs with this, default 30 there)."),
    "MX_KVSTORE_BARRIER_TIMEOUT": ("120", "Seconds a kvstore server BARRIER waits for stragglers before failing the barrier."),
    "MX_KVSTORE_HEARTBEAT": ("5", "dist_async client: seconds between background PINGs to each server (0 disables); keeps a compute-bound worker from being evicted as stale."),
    "MX_KVSTORE_STALE_TIMEOUT": ("30", "kvstore server: a worker silent this many seconds is evicted from barrier accounting so a wedged peer cannot hold BARRIER forever."),
    "MX_FAULT_INJECT": ("", "Fault-injection spec 'site:action[:k=v,...];...' armed at import (tools/launch.py --fault); see mxnet_tpu/fault.py."),
    "MX_NAN_POLICY": ("", "fit-loop gradient guard (mxnet_tpu/health.py): 'warn' logs non-finite gradients, 'skip_batch' additionally drops the poisoned update so params stay finite, 'raise' fails the rank fast for the supervisor to restart; empty disables."),
    "MX_STEP_TIMEOUT": ("", "Seconds a training step may stall before the watchdog thread dumps every thread's stack to stderr and exits the process with code 86, so tools/launch.py --restart on-failure restarts the rank from its last checkpoint; empty disables."),
    "MX_HEARTBEAT_FILE": ("", "Per-rank liveness file the fit loop atomically rewrites every batch; tools/launch.py --hang-timeout sets it per worker and reads the mtime to tell a slow rank (fresh file) from a wedged one (stale file, killed + restarted)."),
    "MX_RECORDIO_TOLERATE_CORRUPT": ("0", "1 = a corrupt/truncated .rec record (e.g. a tail torn by a mid-write crash) is skipped-and-counted (reader.corrupt_skipped) and reads end there, instead of raising OSError with the uri and byte offset."),
    "MX_FLASH_BLOCK_Q": ("256", "Pallas flash-attention query-block rows (VMEM tiling knob; sweepable on hardware)."),
    "MX_FLASH_BLOCK_K": ("256", "Pallas flash-attention key-block rows."),
    "MX_NO_CAPTURE_FALLBACK": ("0", "bench.py: never replay a TPU capture (the capture loop's own children set this)."),
    "MX_TELEMETRY": ("1", "Runtime telemetry (mxnet_tpu/telemetry.py): 1 (default) records per-phase step histograms (data_wait/forward/backward/exchange/optimizer_apply/metric_update/metric_drain/retrace/compiled_step) into the process-wide instrument registry and appends one flight-recorder step record per training step (phase durations, dispatch/wire deltas, retry + NaN-guard hits, throughput); 0 disables both (spans become shared no-ops).  Engine counters (dispatch_count, wire_bytes, compiled_steps) live in the registry regardless - this flag gates only the span/record layer."),
    "MX_TELEMETRY_TRACE": ("", "Directory for per-process distributed trace files: when set, every span (step phases, kvstore client RPCs, server handling incl. retry/replay events, causally linked by wire-propagated trace/span IDs) is buffered and flushed to <dir>/trace-<role>-r<rank>-p<pid>.trace.json at process exit; tools/telemetry_dump.py merges the per-worker files into one chrome-trace timeline.  Empty disables span buffering (tests force it via telemetry.start_tracing())."),
    "MX_TELEMETRY_RING": ("256", "Flight-recorder capacity: the telemetry ring keeps the last N structured step records, dumped to MX_CRASH_DIR on watchdog/NaN/fit failure and summarized (step, throughput, last-exchange bytes) in the heartbeat file's JSON payload for the supervisor's fleet status table."),
    "MX_CRASH_DIR": ("", "Crash-dump directory: on a watchdog trip, an MX_NAN_POLICY=raise gradient guard, a fit-loop exception, or a supervisor-observed rank failure, the flight-recorder ring + a counters snapshot are written to <dir>/crash-rank<r>-pid<p>-<n>.json (the supervisor adds supervisor-<proc>-<n>.json with what it saw: exit code, restarts, last heartbeat payload).  Empty disables crash dumps."),
    "MX_SERVE_BUCKETS": ("1,2,4,8,16", "Serving engine (mxnet_tpu/serve): comma-separated batch-size buckets the AOT compiler pre-traces per servable version.  Every batch the micro-batcher dispatches is padded up to the smallest bucket that fits, so serve-time never pays a trace; requests larger than the top bucket are rejected at admission."),
    "MX_SERVE_MAX_BATCH": ("16", "Serving engine: the micro-batcher coalesces queued requests into one dispatch of at most this many rows (clamped to the top MX_SERVE_BUCKETS bucket).  Larger batches amortize dispatch overhead at higher per-request latency."),
    "MX_SERVE_MAX_DELAY_US": ("2000", "Serving engine: microseconds the micro-batcher holds an under-full batch open for more arrivals before dispatching what it has.  0 dispatches immediately (no coalescing).  The wait rides the mxnet_tpu.fault injectable clock, so virtual-time tests drive the coalescing window deterministically."),
    "MX_SERVE_QUEUE_CAP": ("256", "Serving engine: admission-queue bound in ROWS (requests' batch rows, not request count).  A submit that would exceed it is rejected immediately with an explicit overload error (counted in serve.rejected) instead of queueing into unbounded latency - load shedding is the backpressure contract."),
    "MX_SERVE_PORT": ("9700", "Port a serving replica binds (python -m mxnet_tpu.serve); with --port-base under the launcher each rank serves on port-base + MX_PROCESS_ID."),
    "MX_SERVE_ROOTS": ("", "Comma-separated serving replica addresses host:port the ServeClient connects to; the client sticks to one replica and fails over to the next on a connection error or timeout (SEQ retry makes the replay safe)."),
    "MX_SERVE_TIMEOUT": ("30", "Seconds a serving client waits for one PREDICT reply (queue wait + dispatch included) before treating the replica as dead and failing over; also the server-side bound on a request waiting out its batch future."),
    "MX_TPU_PROBE_TIMEOUT": ("120", "Seconds the subprocess accelerator probe (base.probe_accelerator, the default budget when no explicit timeout is passed; tests/conftest.py's MX_TEST_CTX=tpu lane reads it the same way) waits for jax backend init before declaring the TPU tunnel wedged.  A timeout is definitive (hangs don't flake); the test suite shrinks it to prove the skip path without burning the full production budget.  Callers that pass an explicit timeout (tools/tpu_capture.py polling) are unaffected."),
    "MX_SERVE_REPLAY_CAP": ("512", "Serving replica: bound on the exactly-once replay cache (one entry per client id).  Entries are kept in LRU order - every new seq or replay hit from a client moves it to the recent end - and over-cap inserts evict the least-recently-touched RESOLVED entries (in-flight entries are never dropped); each eviction is counted in serve.replay_evicted.  Values < 1 clamp to 1 (the exactly-once contract needs at least the in-flight entry; 0 never means 'unbounded').  Serving clients are ephemeral uuids, so without this bound every dead client's last PREDICT response would be retained forever."),
    "MX_SERVE_DECODE_SLOTS": ("8", "Decode engine (mxnet_tpu/serve/decode.py): number of concurrent generation slots in the device-resident KV-cache pool.  The pool is allocated once at deploy (owner 'kv_cache' in the buffer census) and donated through every decode step, so HBM stays flat; decode programs are AOT-bucketed by active-slot count (powers of two up to this), and the continuous-batching pump packs all active sequences into the smallest covering bucket each step - one device dispatch per decode step regardless of the active count."),
    "MX_SERVE_DECODE_MAX_TOKENS": ("32", "Decode engine: cap on generated tokens per GENERATE request (a request's max_tokens clamps to this).  Together with the top prompt bucket it sizes each slot's KV page capacity."),
    "MX_SERVE_DECODE_PAGE": ("16", "Decode engine: KV page size in token positions.  Each slot's cache extent (top prompt bucket + max tokens + the pipeline-overrun margin) rounds up to whole pages; retiring a sequence 'evicts' its pages by bookkeeping alone (lengths reset on slot reuse, stale entries masked) - the pool itself is never reallocated."),
    "MX_SERVE_DECODE_PROMPT_BUCKETS": ("4,8,16", "Decode engine: comma-separated prompt-length buckets the prefill program table pre-compiles.  A GENERATE prompt pads up to the smallest covering bucket (one prefill dispatch per admitted sequence); prompts longer than the top bucket are rejected at admission, so serve time never pays a trace."),
    "MX_SERVE_KV_PAGES": ("0", "Paged decode engine (ISSUE 18): number of physical pages in the shared KV page heap (layers, kv_pages, kv_page_len, heads, head_dim), owner 'kv_pages' in the buffer census.  0 (default) auto-sizes to (slots+1) * pages-per-slot - the same HBM the flat pool would take - but because sessions only hold the pages their actual length needs, the same heap admits several times more mixed-length sessions.  > 0 on 'python -m mxnet_tpu.serve --decode' also SELECTS the paged engine (the flat pool stays the default).  Page 0 is reserved scratch."),
    "MX_SERVE_KV_PAGE_LEN": ("0", "Paged decode engine: token positions per physical KV page.  0 (default) inherits MX_SERVE_DECODE_PAGE.  Smaller pages pack mixed-length sessions tighter and share longer prefixes (only FULL pages are hash-shared); larger pages cut block-table and gather overhead."),
    "MX_SERVE_PREFIX_SHARE": ("1", "Paged decode engine: 1 (default) hash-shares read-only full prompt pages across sessions - a rolling content hash over token ids is chained at page boundaries, equal hashes adopt the donor's pages via refcounts, and a session diverging inside a shared page forks it copy-on-write - so N sessions over one system prompt prefill only their suffixes.  0 disables sharing (every admission prefills all its pages)."),
    "MX_SERVE_PREFILL_CHUNK": ("0", "Paged decode engine: prefill chunk length in token positions (rounded up to whole pages; 0 = one page).  Long prompts prefill as a train of page-aligned chunks that INTERLEAVE with decode steps inside the pump's one-dispatch-per-tick cadence, so a 10k-token admission never stalls in-flight generations for more than one chunk-step."),
    "MX_SERVE_SPEC_K": ("4", "Speculative decoding (ISSUE 20): tokens the draft model proposes per speculative window.  Each window costs spec_k draft dispatches (on the draft's own tiny KV pool) + ONE multi-position verify dispatch on the paged target, which accepts the longest agreeing prefix and emits the target's own argmax after it - so 1..spec_k tokens commit per verify with output BIT-IDENTICAL to non-speculative greedy decode regardless of draft quality.  Clamped to [1, 8] (the page-overrun margin the verify scatter needs)."),
    "MX_SERVE_DRAFT": ("0", "Speculative decoding: number of layers in the built-in draft model for 'python -m mxnet_tpu.serve --decode'.  > 0 co-hosts a shallow draft (the target demo LM's first N layers, shared embeddings - see demo_spec_pair) next to the paged target and selects the speculative engine; requires MX_SERVE_KV_PAGES > 0.  0 (default) disables speculation."),
    "MX_SERVE_HBM_BUDGET": ("0", "Census-driven multi-model bin-packing (ISSUE 20): HBM byte budget one serving replica may spend across every co-hosted model (deployed servables + decode engines' target/draft).  ModelHost.deploy measures each candidate AFTER its warm - live param/state bytes plus the peak memory_analysis temp bytes of its registered programs - and refuses admission with a typed in-band '(False, \"budget: ...\")' wire reply when hosted + new would bust the budget.  0 (default) disables the packer (admission is unbounded)."),
    "MX_PROGRAM_CENSUS": ("1", "XLA program census (mxnet_tpu/programs.py): 1 (default) routes every jit-creation site through the process-wide program registry - per-program compile-time histograms (program_compile_seconds{program}), XLA memory_analysis/cost_analysis metadata (program_temp_bytes/program_flops, where the backend provides them), retrace counts with a structured retrace-explainer diff (which arg's shape/dtype/tree structure changed), and the jax.live_arrays() device-buffer census bucketed by owner (params/optimizer_state/ef_residuals/serve/other) riding flight-recorder records and crash dumps.  0 makes register_program a plain jax.jit and disables the census."),
    "MX_LEAK_WARN_BYTES": ("67108864", "Buffer-census leak detector threshold: when total live device bytes grow monotonically across consecutive census checks by more than this many bytes, the census_leak_bytes gauge latches the streak, census.leak_trips increments and a warning names the growing owner buckets.  Any shrink resets the streak; 0 disables the trip (gauges still publish)."),
    "MX_BENCH_HISTORY": ("", "Path of the bench-trajectory history file tools/bench_compare.py appends each bench.py run to and gates regressions against (>10% throughput or >15% peak-temp-bytes vs the rolling best per metric); empty uses BENCH_HISTORY.jsonl next to bench.py."),
    "MX_FLEET_INTERVAL": ("2.0", "Fleet collector (mxnet_tpu/fleet.py): seconds between scrape rounds over every registered member (serve replicas + PS servers via the METRICS wire verb, training workers via their heartbeat files' JSON payload).  A member that fails its scrape is marked absent on that same round.  0 disables the embedded supervisor collector."),
    "MX_FLEET_RING": ("120", "Fleet collector: bounded time-series ring of merged fleet snapshots (one entry per scrape round, keyed (role, rank, instrument) inside).  The straggler/SLO detectors and tools/fleet_top.py read the ring; the newest entry rides supervisor crash dumps as the `fleet` section."),
    "MX_FLEET_WINDOW": ("5", "Fleet detectors: sliding-window length in scrape rounds for straggler step-time medians and SLO burn (rolling p50/p99, rejection-rate) computation.  Short windows react faster; long windows smooth transients."),
    "MX_FLEET_STRAGGLER_FACTOR": ("2.0", "Straggler detector: a worker whose windowed step duration exceeds this multiple of the fleet (lower-)median is flagged — fleet.stragglers gauge, a flight-recorder event and a structured warning naming the rank and its dominant phase (e.g. data_wait)."),
    "MX_FLEET_STALE": ("", "Fleet collector: seconds a heartbeat-scraped member's beat may age before the member is marked absent.  Empty = auto: max(2x MX_FLEET_INTERVAL, 30s) - beats are per BATCH, so the floor stays above slow-rank step times (a 6s-step straggler must be NAMED, not flap absent).  Wire-scraped members (serve/PS) are instead marked absent on scrape failure."),
    "MX_FLEET_SLO_P50_MS": ("", "Serving SLO target: fleet-merged rolling p50 of the MX_FLEET_SLO_PHASES histograms in milliseconds.  fleet.slo_burn{slo=p50_latency} publishes observed/target; burn > 1 latches a breach event.  Empty disables this tracker."),
    "MX_FLEET_SLO_P99_MS": ("", "Serving SLO target: fleet-merged rolling p99 latency in milliseconds (same burn/latch semantics as MX_FLEET_SLO_P50_MS).  Empty disables."),
    "MX_FLEET_SLO_REJECT_RATE": ("", "Serving SLO target: windowed fleet rejection-rate bound (rejected / (requests+rejected), from merged serve.* counter deltas).  Burn = observed/target into fleet.slo_burn{slo=rejection_rate}; > 1 latches.  Empty disables."),
    "MX_FLEET_SLO_QUEUE": ("", "Serving SLO target: mean fleet queue depth bound (rows, from merged serve.queue_rows gauges).  Burn = observed/target into fleet.slo_burn{slo=queue_depth}; > 1 latches.  Empty disables."),
    "MX_FLEET_SLO_PHASES": ("queue_wait,serve_dispatch", "Comma-separated step_phase_seconds phases whose fleet-merged histograms define the serving latency distribution the SLO p50/p99 trackers read (bucket-wise exact merge; identical boundaries required)."),
    "MX_COMPILE_CACHE": ("", "Persistent compiled-program cache directory (mxnet_tpu/compile_cache.py): every AOT jit site routed through the program registry serializes its XLA executable here, keyed by (program name, trace signature, function fingerprint, jit spec, backend/topology/jax-version/library-fingerprint envelope), so a warm restart — supervisor respawn, chaos restart, serve replica spawn — DESERIALIZES (~ms) instead of re-tracing and re-compiling (seconds).  jax's own persistent compilation cache is additionally armed under <dir>/xla for the light-mode sites an executable store cannot key (the hybridize train lane's vjp closures).  Any miss, version skew or corrupt entry is counted (compile_cache.misses{reason}) and falls back to a normal compile — the cache can never fail a program.  Writes are temp+rename atomic; concurrent writers are last-write-wins.  Empty disables both layers."),
    "MX_COMPILE_CACHE_SALT": ("", "Extra compile-cache key component: operators set it to partition one shared cache directory (e.g. per experiment branch) without deleting entries; changing it is a guaranteed full-miss restart."),
    "MX_PREFETCH": ("1", "Async device input pipeline (mxnet_tpu/io/prefetch.py DevicePrefetcher) in the harnesses that support it (bench.py --eager): a background thread device_puts one batch AHEAD of the training loop (double-buffered), so the host->device transfer of batch N+1 overlaps the compute of batch N and the loop's data_wait phase share collapses to the queue handoff.  Bit-parity with the synchronous path (device_put moves bytes, never rounds).  0 keeps the transfer synchronous in the loop (still measured under data_wait)."),
    "MX_PREFETCH_DEPTH": ("2", "DevicePrefetcher queue bound in batches: how many device-resident batches may sit ahead of the consumer (2 = classic double buffering).  The producer blocks (stop-aware bounded polls) at the bound, so prefetch can never balloon memory by more than this many batches."),
    "MX_ELASTIC": ("0", "Elastic membership (mxnet_tpu/kvstore): 1 = a dist_async worker announces itself with the JOIN wire verb at store init (idempotent for ranks the server already seeded) and the Module.fit loop installs a SIGTERM drain handler — on preemption notice the rank finishes its epoch, checkpoints, sends LEAVE and exits 0, so the barrier quorum shrinks instead of timing out.  tools/launch.py --elastic sets it for every worker.  0 keeps the fixed-membership behavior."),
    "MX_ELASTIC_EPOCH": ("0", "The membership epoch a worker incarnation plans its fusion buckets under (the bucket-name CRC salt).  Set by tools/launch.py --elastic on every (re)spawned worker after a resize, so all workers of one incarnation derive identical salted bucket names with no coordination; 0 keeps the historical unsalted names."),
    "MX_ELASTIC_EVICT_AFTER": ("", "kvstore server: a MEMBER rank silent this many seconds is evicted from the live membership table itself (an involuntary LEAVE with a membership-epoch bump) instead of only being discounted from the current barrier - shrink-and-continue for workers that died without preemption notice.  Empty/0 disables permanent eviction (transient stale discounting via MX_KVSTORE_STALE_TIMEOUT still applies)."),
    "MX_EXCHANGE_HIERARCHICAL": ("0", "1 = two-tier gradient exchange on the dist_async store (gradient/accumulate mode): tier 1 merges device copies locally (ICI), tier 2 ships int8 both ways across the slice boundary - the existing compressed PUSH plus the PULLQ quantized return leg - with each fusion bucket's pull launched as-ready on its own connection (a straggling server shard delays only its own buckets).  Cross-slice wire bytes drop ~4x vs the flat fp32 pull; the pull leg's quantization error is stateless (no error feedback), so this is an opt-in for the accumulate exchange, never the default."),
    "MX_EXCHANGE_PARALLEL": ("4", "Concurrent as-ready bucket pulls (dedicated connections) per worker under MX_EXCHANGE_HIERARCHICAL."),
    "MX_FLEET_PORT": ("", "Port the fleet collector's wire server binds (FLEET verb -> merged snapshot as a JSN payload, METRICS -> whole-fleet federation exposition; same length-prefixed envelope as the kvstore/serve wire).  This is the API surface the coming serve router/autoscaler consume.  Empty = no wire server."),
    "MX_FLEET_HTTP_PORT": ("", "Port of the collector's Prometheus federation HTTP endpoint: GET /metrics returns every member's instruments re-labeled {role,rank,model} plus the fleet rollups — a single scrape covers the whole fleet; GET /fleet.json returns the merged snapshot.  Empty = no HTTP endpoint."),
    "MX_SERVE_DRAIN_TIMEOUT": ("30", "Serving replica drain-not-kill retirement (ISSUE 17): default bounded deadline in seconds a DRAIN verb without an explicit timeout arms.  Admission closes immediately (fresh PREDICT/GENERATE answered '(False, draining: ...)' so routers/clients re-route), in-flight requests and generations finish, then the serve loop exits cleanly; past the deadline the stragglers' connections are severed with NO reply so their clients fail over and re-prefill on a survivor.  A re-asserted DRAIN keeps the FIRST deadline (a retry cannot extend retirement)."),
    "MX_ROUTER_PORT": ("9800", "Port the serving front-tier router binds (python -m mxnet_tpu.serve.router) when --port is not given.  Clients point MX_SERVE_ROOTS at this one address and the router forwards their SEQ envelopes verbatim across the replica set."),
    "MX_ROUTER_REPLICAS": ("", "Comma-separated static replica addresses host:port the router seeds its membership with (the dynamic complement is MX_ROUTER_REPLICAS_FILE).  New members join 'up' optimistically; the first failed forward demotes them to 'dead' and a connect-probe per refresh tick revives them."),
    "MX_ROUTER_REPLICAS_FILE": ("", "Path of the authoritative replica-list file (one host:port per line, '#' comments) the router re-reads every refresh tick.  tools/launch.py --route rewrites it atomically as the autoscaler spawns and retires replicas: an addr that appears joins 'up', one that disappears goes 'draining' (nothing new routed there) until dead, then is forgotten."),
    "MX_ROUTER_REFRESH": ("1.0", "Seconds between router refresh ticks: replicas-file re-read, dead-replica connect probes, and the FLEET snapshot pull that feeds least-loaded routing.  Also the router's heartbeat cadence under the launcher's --hang-timeout."),
    "MX_ROUTER_FLEET": ("", "Fleet collector wire address host:port the router pulls merged load signals from (fleet.replica_signals projection: queue depth, decode admission queue, decode slot occupancy, KV headroom).  Empty = no signals; routing degrades to round-robin over 'up' replicas (a fresh replica with no scrape history scores 0 = idle, which is correct)."),
    "MX_ROUTER_PIN_CAP": ("4096", "Bound on the router's session-pin LRU (client_id -> replica).  Serving clients are ephemeral uuids, so pins must age out; evicting a pin costs decode locality on that session's NEXT request (it re-routes least-loaded and re-pins), never correctness.  Values < 1 clamp to 1."),
    "MX_ROUTER_DRAIN_TIMEOUT": ("30", "Default bounded deadline in seconds for draining the ROUTER itself (DRAIN verb to the router): new sessions are refused 'draining: ...' while pinned sessions keep flowing; the router exits once the wire is idle, and past the deadline straggler connections are severed so their clients replay elsewhere."),
    "MX_AUTOSCALE_UP_BURN": ("1.0", "Autoscaler (tools/launch.py --autoscale MIN:MAX): scale UP when any fleet SLO burn (fleet.slo_burn, observed/target from the merged snapshot) meets/exceeds this for MX_AUTOSCALE_HOLD consecutive supervisor ticks.  Spawns one warm replica per decision (compile-cache restarts make this seconds, not minutes) and registers it with the collector + the router's replicas file."),
    "MX_AUTOSCALE_DOWN_BURN": ("0.5", "Autoscaler: scale DOWN (retire-and-drain ONE replica) when every tracked SLO burn stays at/below this for MX_AUTOSCALE_HOLD consecutive ticks.  The gap between UP_BURN and DOWN_BURN is the hysteresis band that keeps the fleet from flapping; retirement is always drain-not-kill (DRAIN verb, bounded deadline, supervisor treats the clean exit as expected)."),
    "MX_AUTOSCALE_HOLD": ("3", "Autoscaler: consecutive supervisor autoscale ticks a burn signal must hold before acting (both directions).  Raising it trades reaction time for stability; 1 reacts on a single tick."),
    "MX_AUTOSCALE_COOLDOWN": ("10", "Autoscaler: base seconds of the post-action cooldown.  Each action arms fault.RetryPolicy-style backoff (base * 2^consecutive-same-direction-actions, jittered, capped at 8x) before the next action may fire, so a spike absorbs with a burst of spawns but repeated flip-flops back off exponentially."),
}


def get_env(name: str, default: Any = None, dtype: Callable = str) -> Any:
    """Read an env flag with overrides (reference: dmlc::GetEnv)."""
    with _env_lock:
        if name in _env_overrides:
            val = _env_overrides[name]
        else:
            val = os.environ.get(name)
    if val is None:
        if default is None and name in ENV_CATALOG:
            default = ENV_CATALOG[name][0]
        if default is None:
            return None
        val = default
    try:
        if dtype is bool:
            return str(val).lower() in ("1", "true", "yes", "on")
        return dtype(val)
    except (TypeError, ValueError):
        return default


def set_env(name: str, value: Optional[str]) -> None:
    """Set (or with None, unset) a process-local env override.  NB this
    keeps os.environ in sync, which hot-path caches (engine.is_naive's
    value-compare) rely on.  Unsetting REMOVES the override entirely —
    a lingering ``None`` entry would shadow every later direct
    ``os.environ`` write (e.g. pytest ``monkeypatch.setenv``) behind
    the catalog default forever."""
    with _env_lock:
        if value is None:
            _env_overrides.pop(name, None)
            os.environ.pop(name, None)
        else:
            _env_overrides[name] = str(value)
            os.environ[name] = str(value)


class environment:
    """Context manager scoping env-var changes (reference:
    python/mxnet/test_utils.py (environment))."""

    def __init__(self, *args):
        if len(args) == 1 and isinstance(args[0], dict):
            self._kwargs = dict(args[0])
        elif len(args) == 2:
            self._kwargs = {args[0]: args[1]}
        else:
            raise ValueError("environment() takes (name, value) or a dict")
        self._saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        for k, v in self._kwargs.items():
            self._saved[k] = os.environ.get(k)
            set_env(k, v)
        return self

    def __exit__(self, *exc):
        for k, v in self._saved.items():
            set_env(k, v)
        return False


# ---------------------------------------------------------------------------
# Accelerator tunnel health (TPU-via-axon deployments).  A wedged tunnel makes
# jax backend init HANG (not error) — and the axon plugin force-sets
# jax.config jax_platforms="axon,cpu", overriding the JAX_PLATFORMS env var.
# These helpers are the single implementation behind bench.py, the driver
# entry points and any tool that must never hang on a dead tunnel.
# ---------------------------------------------------------------------------

def cpu_pinned_by_user() -> bool:
    """True if the operator explicitly pinned CPU (MX_FORCE_CPU truthy or
    JAX_PLATFORMS=cpu) — callers must honor it and skip accelerator probes.
    Same bool parsing as device.py's resolution ('1'/'true'/'yes'/'on'),
    so the pin and the probe can never disagree."""
    if get_env("MX_FORCE_CPU", dtype=bool):
        return True
    return os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"


_probe_result: Optional[bool] = None


def probe_timeout() -> float:
    """MX_TPU_PROBE_TIMEOUT: subprocess budget for one accelerator
    probe.  Env-tunable so the test lane can prove the skip path
    without burning the full production budget on a wedged tunnel."""
    try:
        return float(get_env("MX_TPU_PROBE_TIMEOUT", 120.0, float))
    except (TypeError, ValueError):
        return 120.0


def probe_accelerator(timeout_s: Optional[float] = None) -> bool:
    """True iff jax's default backend is a healthy accelerator.

    Probed in a SUBPROCESS with a hard timeout (default: the cataloged
    MX_TPU_PROBE_TIMEOUT budget via :func:`probe_timeout`): in-process
    backend init on a wedged tunnel blocks forever with no way to
    recover.  A probe timeout is treated as definitively wedged (hangs
    don't flake) — no retry.  The result is memoized for the process
    lifetime (the probe costs a full jax startup, and the
    wedged/healthy state doesn't change underneath one process by the
    same hangs-don't-flake reasoning)."""
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    _probe_result = probe_accelerator_once(
        probe_timeout() if timeout_s is None else timeout_s)
    return _probe_result


def probe_accelerator_once(timeout_s: float = 120.0) -> bool:
    """One un-memoized subprocess probe (see probe_accelerator).  Polling
    loops (tools/tpu_capture.py) use this directly — a tunnel that heals
    mid-round must be observable across repeated calls."""
    import subprocess
    import sys
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("MX_FORCE_CPU", None)
    code = "import jax; d = jax.devices(); assert jax.default_backend() != 'cpu'"
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           timeout=timeout_s,
                           stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        # wedged: hangs don't flake, and a quick rc!=0 (no plugin) is
        # deterministic — one attempt decides either way
        return False


def pin_cpu() -> None:
    """Pin jax to the cpu backend via config (the env var alone is NOT
    enough: the axon plugin overrides it with jax.config.update)."""
    import jax
    jax.config.update("jax_platforms", "cpu")


def ensure_live_backend(timeout_s: Optional[float] = None) -> str:
    """Honor an explicit user CPU pin; otherwise probe the accelerator and
    pin cpu if it is wedged.  Returns "cpu" or "accelerator".  The probe
    budget defaults to MX_TPU_PROBE_TIMEOUT (forwarded as None so
    probe_accelerator resolves it), like every no-explicit-timeout
    probe path."""
    if cpu_pinned_by_user():
        pin_cpu()
        return "cpu"
    if probe_accelerator(timeout_s):
        return "accelerator"
    pin_cpu()
    return "cpu"
