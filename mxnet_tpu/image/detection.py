"""Detection data pipeline: ImageDetIter + label-aware augmenters.

Reference: python/mxnet/image/detection.py (class ImageDetIter,
DetHorizontalFlipAug, DetRandomCropAug, DetBorderAug, CreateDetAugmenter)
— the SSD training input path (example/ssd/train.py feeds exactly this).

Label format (the reference's .rec det convention,
tools/im2rec.py --pack-label): header.label is a flat float vector
``[header_width, object_width, <extra header...>, obj0..., obj1...]``
with each object ``[class_id, xmin, ymin, xmax, ymax, ...]`` in
COORDINATES NORMALIZED to [0, 1].  The iterator pads every image's
objects to the dataset-wide max (padded rows are -1) so batches are
rectangular — the shape MultiBoxTarget expects.
"""
from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from . import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
               ForceResizeAug, imdecode)

# Per-record RNG plumbing: ImageDetIter seeds a thread-local RandomState
# from (iterator seed, record key, epoch) before running the augmenter
# chain, so augmentation is DETERMINISTIC regardless of worker-thread
# scheduling, and no RandomState is ever shared across threads.
_TL = threading.local()


def _det_rng() -> _np.random.RandomState:
    rng = getattr(_TL, "rng", None)
    if rng is None:                       # standalone augmenter use
        rng = _np.random.RandomState()
        _TL.rng = rng
    return rng

__all__ = ["ImageDetIter", "DetHorizontalFlipAug", "DetRandomCropAug",
           "DetBorderAug", "CreateDetAugmenter"]


class DetAugmenter:
    """Augmenter that transforms (image, label) together (reference:
    DetAugmenter).  label: (N, 5+) [cls, x1, y1, x2, y2] normalized."""

    def __call__(self, src, label):
        raise NotImplementedError


class _DetImageOnly(DetAugmenter):
    """Lift a color/cast-style image augmenter that never moves pixels."""

    def __init__(self, aug: Augmenter):
        self.aug = aug

    def __call__(self, src, label):
        return self.aug(src), label


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates together (reference:
    DetHorizontalFlipAug)."""

    def __init__(self, p=0.5):
        self.p = p

    def __call__(self, src, label):
        if _det_rng().rand() < self.p:
            arr = src.asnumpy()[:, ::-1, :]
            from . import _to_nd
            src = _to_nd(arr.copy())
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference: DetRandomCropAug — the SSD
    paper's sampling strategy).  Tries up to `max_attempts` crops whose
    min-IoU with some object exceeds a sampled constraint; objects whose
    CENTER falls outside the crop are dropped; coordinates re-normalized."""

    def __init__(self, min_object_covered=0.3,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.3, 1.0), max_attempts=20):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[0], src.shape[1]
        valid = label[:, 0] >= 0
        if not valid.any():
            return src, label
        boxes = label[valid, 1:5]
        rng = _det_rng()
        for _ in range(self.max_attempts):
            scale = rng.uniform(*self.area_range)
            ratio = rng.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(scale * ratio))
            ch = min(1.0, _np.sqrt(scale / ratio))
            cx0 = rng.uniform(0, 1 - cw)
            cy0 = rng.uniform(0, 1 - ch)
            crop = _np.array([cx0, cy0, cx0 + cw, cy0 + ch])
            ix1 = _np.maximum(boxes[:, 0], crop[0])
            iy1 = _np.maximum(boxes[:, 1], crop[1])
            ix2 = _np.minimum(boxes[:, 2], crop[2])
            iy2 = _np.minimum(boxes[:, 3], crop[3])
            inter = _np.maximum(ix2 - ix1, 0) * _np.maximum(iy2 - iy1, 0)
            area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
            cover = inter / _np.maximum(area, 1e-12)
            if (cover >= self.min_object_covered).any():
                return self._apply(src, label, crop, h, w)
        return src, label

    def _apply(self, src, label, crop, h, w):
        x0, y0 = int(crop[0] * w), int(crop[1] * h)
        x1, y1 = int(crop[2] * w), int(crop[3] * h)
        arr = src.asnumpy()[y0:y1, x0:x1, :]
        from . import _to_nd
        out = label.copy()
        cw, ch = crop[2] - crop[0], crop[3] - crop[1]
        for i in range(out.shape[0]):
            if out[i, 0] < 0:
                continue
            cx = (out[i, 1] + out[i, 3]) / 2
            cy = (out[i, 2] + out[i, 4]) / 2
            if not (crop[0] <= cx <= crop[2] and crop[1] <= cy <= crop[3]):
                out[i] = -1.0        # center left the crop: drop object
                continue
            out[i, 1] = _np.clip((out[i, 1] - crop[0]) / cw, 0, 1)
            out[i, 3] = _np.clip((out[i, 3] - crop[0]) / cw, 0, 1)
            out[i, 2] = _np.clip((out[i, 2] - crop[1]) / ch, 0, 1)
            out[i, 4] = _np.clip((out[i, 4] - crop[1]) / ch, 0, 1)
        return _to_nd(arr.copy()), out


class DetBorderAug(DetAugmenter):
    """Zoom-out / expand padding (reference: DetBorderAug): place the image
    on a larger mean-filled canvas, shrinking boxes accordingly."""

    def __init__(self, max_expand=2.0, fill=127, p=0.5):
        self.max_expand = max_expand
        self.fill = fill
        self.p = p

    def __call__(self, src, label):
        rng = _det_rng()
        if rng.rand() >= self.p:
            return src, label
        h, w, c = src.shape
        ratio = rng.uniform(1.0, self.max_expand)
        nh, nw = int(h * ratio), int(w * ratio)
        oy = rng.randint(0, nh - h + 1)
        ox = rng.randint(0, nw - w + 1)
        canvas = _np.full((nh, nw, c), self.fill, src.asnumpy().dtype)
        canvas[oy:oy + h, ox:ox + w, :] = src.asnumpy()
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * w + ox) / nw
        out[valid, 3] = (out[valid, 3] * w + ox) / nw
        out[valid, 2] = (out[valid, 2] * h + oy) / nh
        out[valid, 4] = (out[valid, 4] * h + oy) / nh
        from . import _to_nd
        return _to_nd(canvas), out


class _DetForceResize(DetAugmenter):
    """Resize to the network input size — normalized labels are invariant."""

    def __init__(self, size: Tuple[int, int], interp=2):
        self.aug = ForceResizeAug(size, interp)

    def __call__(self, src, label):
        return self.aug(src), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0.0, rand_pad=0.0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 1.0), max_expand=2.0,
                       pad_val=127, inter_method=2, **_kw):
    """Standard SSD augmentation chain (reference: CreateDetAugmenter)."""
    augs: List[DetAugmenter] = []
    if rand_crop > 0:
        augs.append(DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                     area_range))
    if rand_pad > 0:
        augs.append(DetBorderAug(max_expand, pad_val, rand_pad))
    augs.append(_DetForceResize((data_shape[2], data_shape[1]),
                                inter_method))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        augs.append(_DetImageOnly(ColorJitterAug(brightness, contrast,
                                                 saturation)))
    augs.append(_DetImageOnly(CastAug()))
    if mean is not None or std is not None:
        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53], _np.float32)
        if std is True:
            std = _np.array([58.395, 57.12, 57.375], _np.float32)
        augs.append(_DetImageOnly(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter(DataIter):
    """Detection batches from an indexed .rec (reference: ImageDetIter).

    Yields DataBatch(data=(B, C, H, W) float, label=(B, max_obj, 5))."""

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, aug_list=None,
                 mean=None, std=None, rand_crop=0.0, rand_pad=0.0,
                 rand_mirror=False, preprocess_threads=4, seed=0,
                 num_parts=1, part_index=0, dtype="float32", **kw):
        super().__init__(batch_size)
        from .. import recordio
        self.data_shape = tuple(data_shape)
        self._dtype = _np.dtype(dtype)
        self._idx_path = path_imgidx or path_imgrec[:-4] + ".idx"
        self._record = recordio.MXIndexedRecordIO(self._idx_path,
                                                  path_imgrec, "r")
        keys = self._record.keys
        if not keys:
            raise MXNetError("ImageDetIter needs indexed records (.idx)")
        self._keys = _np.asarray(keys[part_index::num_parts])
        self._shuffle = shuffle
        self._seed = int(seed)
        self._epoch = 0
        self._rng = _np.random.RandomState(seed)
        if aug_list is None:
            aug_list = CreateDetAugmenter(
                (3,) + tuple(self.data_shape[1:]), rand_crop=rand_crop,
                rand_pad=rand_pad, rand_mirror=rand_mirror, mean=mean,
                std=std, **kw)
        self._augs = aug_list
        self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._lock = threading.Lock()
        # one pass over headers to size the label pad (reference: ImageDetIter
        # reads label shapes up front via next_sample)
        self._max_objs = 1
        self._obj_width = 5
        for k in self._keys:
            lab = self._read_label(int(k))
            self._max_objs = max(self._max_objs, lab.shape[0])
        self.reset()

    # -- label parsing ------------------------------------------------------
    def _parse_label(self, flat: _np.ndarray) -> _np.ndarray:
        flat = _np.asarray(flat, _np.float32).ravel()
        if flat.size < 2:
            return _np.full((0, 5), -1.0, _np.float32)
        header_width = int(flat[0])
        obj_width = int(flat[1])
        if obj_width < 5:
            raise MXNetError("det label object_width must be >= 5, got %d"
                             % obj_width)
        body = flat[header_width:]
        n = body.size // obj_width
        objs = body[:n * obj_width].reshape(n, obj_width)[:, :5]
        return objs.astype(_np.float32)

    def _read_label(self, key: int) -> _np.ndarray:
        from .. import recordio as rio
        with self._lock:
            payload = self._record.read_idx(key)
        header, _ = rio.unpack(payload)
        return self._parse_label(_np.asarray(header.label))

    # -- iterator protocol --------------------------------------------------
    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        return [DataDesc("label", (self.batch_size, self._max_objs, 5),
                         _np.float32)]

    def reset(self):
        self._order = self._keys.copy()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0
        with self._lock:
            # the pool workers read _epoch for their per-record seeds;
            # next()'s map() is synchronous so no fetch is in flight
            # here, but the lock makes the publication explicit instead
            # of relying on that calling discipline
            self._epoch += 1

    def _load_one(self, key):
        from .. import recordio as rio
        with self._lock:
            epoch = self._epoch
            payload = self._record.read_idx(int(key))
        # deterministic per (seed, record, epoch) no matter which worker
        # thread picks the record up
        _TL.rng = _np.random.RandomState(
            (self._seed * 1000003 + int(key) * 9176 + epoch)
            % (2 ** 31))
        header, img_bytes = rio.unpack(payload)
        img = imdecode(img_bytes)
        label = self._parse_label(_np.asarray(header.label))
        pad = _np.full((self._max_objs, 5), -1.0, _np.float32)
        for aug in self._augs:
            img, label = aug(img, label) if isinstance(aug, DetAugmenter) \
                else (aug(img), label)
        n = min(label.shape[0], self._max_objs)
        pad[:n] = label[:n]
        arr = img.asnumpy().astype(self._dtype)
        return arr.transpose(2, 0, 1), pad

    def next(self) -> DataBatch:
        from .. import ndarray as nd
        if self._cursor >= len(self._order):
            raise StopIteration
        keys = self._order[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        npad = self.batch_size - len(keys)
        if npad:
            keys = _np.concatenate([keys, self._order[:npad]])
        results = list(self._pool.map(self._load_one, keys))
        data = _np.stack([r[0] for r in results])
        label = _np.stack([r[1] for r in results])
        return DataBatch(data=[nd.array(data)], label=[nd.array(label)],
                         pad=npad)
