"""mx.image — image decode / resize / augmentation.

Reference: ``python/mxnet/image/image.py`` (imdecode, imread, imresize,
resize_short, fixed_crop, center_crop, random_crop, random_size_crop,
color_normalize, Augmenters, CreateAugmenter, ImageIter) over OpenCV +
``src/io/image_aug_default.cc`` (DefaultImageAugmenter).

TPU-first split of responsibilities: *decode and geometric augmentation*
stay on the host (PIL provides the codec; these are per-sample,
variable-shape, branchy — the wrong shape for the MXU), while *color math
on full batches* (normalize, lighting) belongs on device inside the
training step where XLA fuses it with the first conv.  The functions here
mirror the reference's host-side surface and return HWC uint8/float32
NDArrays on cpu; ``ImageIter`` batches to NCHW like the reference's
ImageRecordIter.
"""
from __future__ import annotations

import io as _io
import threading as _threading
import logging
import os
import random as _pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from ..device import cpu
from .. import recordio

__all__ = ["imdecode", "imread", "imresize", "imrotate", "resize_short",
           "fixed_crop", "center_crop", "random_crop", "random_size_crop",
           "color_normalize", "copyMakeBorder",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "CastAug", "HorizontalFlipAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "CreateAugmenter", "ImageIter", "scale_down"]


def _pil():
    from PIL import Image
    return Image


def _to_nd(arr: _np.ndarray) -> NDArray:
    return nd.array(_np.ascontiguousarray(arr), ctx=cpu(), dtype=arr.dtype)


def _to_np(img) -> _np.ndarray:
    return img.asnumpy() if isinstance(img, NDArray) else _np.asarray(img)


# -- codecs -------------------------------------------------------------------

_NATIVE_JPEG = None
_NATIVE_JPEG_TRIED = False


_NATIVE_JPEG_LOCK = _threading.Lock()


def _native_jpeg():
    """ctypes handle on the native libjpeg decoder (src/imdecode.cc) —
    the reference's C++ decode path; None when the toolchain/libjpeg is
    unavailable (PIL fallback).  First call builds under a lock so a
    thread pool's concurrent first batch WAITS for the native path
    instead of silently decoding via PIL."""
    global _NATIVE_JPEG, _NATIVE_JPEG_TRIED
    if _NATIVE_JPEG_TRIED:
        return _NATIVE_JPEG
    with _NATIVE_JPEG_LOCK:
        if _NATIVE_JPEG_TRIED:
            return _NATIVE_JPEG
        try:
            import ctypes
            from .. import _native
            lib = _native.load("imdecode")
            lib.MXImdecode.restype = ctypes.c_int
            lib.MXImdecode.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int)]
            lib.MXImdecodeFree.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte)]
            _NATIVE_JPEG = lib
        except OSError:
            _NATIVE_JPEG = None
        _NATIVE_JPEG_TRIED = True
    return _NATIVE_JPEG


def _imdecode_native(buf: bytes, flag: int):
    lib = _native_jpeg()
    if lib is None:
        return None
    import ctypes
    out = ctypes.POINTER(ctypes.c_ubyte)()
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    rc = lib.MXImdecode(buf, len(buf), 1 if flag == 0 else 3,
                        ctypes.byref(out), ctypes.byref(h), ctypes.byref(w),
                        ctypes.byref(c))
    if rc != 0:
        return None          # not a JPEG / corrupt: PIL path decides
    try:
        n = h.value * w.value * c.value
        arr = _np.ctypeslib.as_array(out, shape=(n,)).reshape(
            h.value, w.value, c.value).copy()
    finally:
        lib.MXImdecodeFree(out)
    return arr


def imdecode(buf: bytes, to_rgb: int = 1, flag: int = 1) -> NDArray:
    """Decode JPEG/PNG bytes → HWC uint8 NDArray (reference: mx.image.imdecode
    → cv::imdecode).  ``flag=0`` decodes grayscale (H, W, 1); to_rgb keeps
    RGB channel order (the reference's default converts BGR→RGB).

    JPEG rides the native GIL-free decoder (src/imdecode.cc, the
    reference's C++ parser role); PNG/other formats and build-less
    environments fall back to PIL."""
    arr = _imdecode_native(bytes(buf), flag)
    if arr is not None:
        if flag != 0 and not to_rgb:
            arr = arr[:, :, ::-1]
        return _to_nd(arr)
    Image = _pil()
    pil = Image.open(_io.BytesIO(buf))
    if flag == 0:
        arr = _np.asarray(pil.convert("L"))[:, :, None]
    else:
        arr = _np.asarray(pil.convert("RGB"))
        if not to_rgb:
            arr = arr[:, :, ::-1]  # BGR, matching OpenCV-style consumers
    return _to_nd(arr)


def imread(filename: str, to_rgb: int = 1, flag: int = 1) -> NDArray:
    """Read + decode an image file (reference: mx.image.imread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb=to_rgb, flag=flag)


_INTERP = {0: "NEAREST", 1: "BILINEAR", 2: "BICUBIC", 3: "LANCZOS",
           4: "LANCZOS", 9: "BILINEAR", 10: "BILINEAR"}


def _resample(interp: int):
    Image = _pil()
    return getattr(Image.Resampling, _INTERP.get(interp, "BILINEAR"))


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    """Resize to exactly (h, w) (reference: mx.image.imresize)."""
    arr = _to_np(src)
    Image = _pil()
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr[:, :, 0] if squeeze else arr)
    out = _np.asarray(pil.resize((w, h), _resample(interp)))
    if squeeze:
        out = out[:, :, None]
    return _to_nd(out)


def imrotate(src, rotation_degrees: float, zoom_in: bool = False,
             zoom_out: bool = False) -> NDArray:
    """Rotate around the center (reference: mx.image.imrotate)."""
    arr = _to_np(src)
    Image = _pil()
    pil = Image.fromarray(arr)
    out = pil.rotate(rotation_degrees, resample=_resample(1),
                     expand=zoom_out)
    out = _np.asarray(out)
    if zoom_out:
        out = _np.asarray(Image.fromarray(out).resize(
            (arr.shape[1], arr.shape[0]), _resample(1)))
    return _to_nd(out)


def resize_short(src, size: int, interp: int = 2) -> NDArray:
    """Scale so the SHORTER side equals size (reference: resize_short)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(arr, new_w, new_h, interp)


def copyMakeBorder(src, top, bot, left, right, *_args, **_kw) -> NDArray:
    """Zero-pad borders (reference: mx.image.copyMakeBorder)."""
    arr = _to_np(src)
    return _to_nd(_np.pad(arr, ((top, bot), (left, right), (0, 0))))


# -- crops --------------------------------------------------------------------

def fixed_crop(src, x0: int, y0: int, w: int, h: int,
               size: Optional[Tuple[int, int]] = None,
               interp: int = 2) -> NDArray:
    arr = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(arr, size[0], size[1], interp)
    return _to_nd(arr)


def center_crop(src, size: Tuple[int, int], interp: int = 2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size: Tuple[int, int], interp: int = 2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size: Tuple[int, int], area, ratio,
                     interp: int = 2, max_attempts: int = 10):
    """Inception-style random area/aspect crop (reference:
    random_size_crop — the ResNet training augmentation)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(max_attempts):
        target_area = _pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


def color_normalize(src, mean, std=None):
    """(x - mean) / std in float32 (reference: color_normalize)."""
    arr = _to_np(src).astype(_np.float32)
    mean = _to_np(mean) if not isinstance(mean, (int, float)) else mean
    arr = arr - mean
    if std is not None:
        std = _to_np(std) if not isinstance(std, (int, float)) else std
        arr = arr / std
    return _to_nd(arr.astype(_np.float32))


# -- augmenters (reference: image.py Augmenter hierarchy) --------------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src: NDArray) -> NDArray:
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts: Sequence[Augmenter]):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts: Sequence[Augmenter]):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        ts = self.ts[:]
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _to_nd(_to_np(src)[:, ::-1])
        return src


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, \
            interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        arr = _to_np(src).astype(_np.float32) * alpha
        return _to_nd(arr)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(_np.float32)
        gray = (arr * self._coef).sum() * (3.0 / arr.size)
        return _to_nd(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(_np.float32)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return _to_nd(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    _tyiq = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], _np.float32)
    _ityiq = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       _np.float32)
        t = self._ityiq @ bt @ self._tyiq
        arr = _to_np(src).astype(_np.float32)
        return _to_nd(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,)).astype(
            _np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _to_nd(_to_np(src).astype(_np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = _np.asarray(mean, _np.float32) \
            if mean is not None else None
        self.std = _np.asarray(std, _np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _to_np(src).astype(_np.float32)
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return _to_nd(_np.broadcast_to(gray, arr.shape).copy())
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Assemble the standard augmentation pipeline (reference:
    image.CreateAugmenter — the ImageRecordIter default chain)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


def scale_down(src_size, size):
    """Scale `size` (w, h) down proportionally to fit within `src_size`
    (w, h) (reference: image.scale_down — crop sizes must not exceed the
    source image; scale_down((640,480),(720,120)) == (640,106))."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


# ImageIter lives with the other iterators; re-exported here for parity
def __getattr__(name):
    if name == "ImageIter":
        from ..io import ImageRecordIter
        return ImageRecordIter
    if name in ("ImageDetIter", "CreateDetAugmenter", "DetAugmenter",
                "DetHorizontalFlipAug", "DetRandomCropAug", "DetBorderAug"):
        from . import detection
        return getattr(detection, name)
    raise AttributeError(name)
