"""Execution-engine semantics over XLA async dispatch.

Reference: src/engine/threaded_engine.cc (ThreadedEngine), naive_engine.cc
(NaiveEngine), include/mxnet/engine.h.

The reference schedules every op as a DAG node over read/write variable
dependencies and runs them on per-device worker threads.  On TPU, PJRT already
gives us exactly those semantics: op dispatch is async, data dependencies are
tracked by buffer definition events, and results only block at explicit sync
points.  What remains of the "engine" is therefore:

  * the sync surface — `wait_to_read` (= block_until_ready), `WaitForAll`;
  * a strict-sync debug mode (`MXNET_ENGINE_TYPE=NaiveEngine`) that blocks
    after every op, used to bisect async-scheduling bugs (SURVEY.md §5.2);
  * deferred-exception propagation: XLA raises device errors at sync points,
    matching the reference's capture-on-worker / rethrow-at-sync contract;
  * bulking (`Engine::StartBulk`): subsumed by XLA fusion — kept as no-ops.
"""
from __future__ import annotations

import jax

from .base import get_env

__all__ = ["Engine", "engine", "is_naive", "wait_all", "set_bulk_size"]


class Engine:
    """Process-wide engine facade (singleton, like Engine::Get())."""

    def __init__(self):
        import weakref
        self._kind_raw = object()   # sentinel: never equals a str
        self._naive = False
        # live NDArray chunks, registered at creation/write; WaitForAll
        # blocks on each — the reference's "wait for all vars" semantics
        self._live = weakref.WeakSet()
        # device-program launches since process start (or the caller's last
        # snapshot): eager op invokes, fused tree updates, kvstore
        # collectives, metric accumulates, whole-graph jit steps.  The
        # dispatch-budget harness (tools/dispatch_count.py) reads deltas of
        # this to pin the O(#buckets)-dispatches-per-step contract.
        self.dispatch_count = 0
        # gradient-exchange payload bytes since process start: what each
        # pushed gradient occupies in its wire representation (compressed
        # codes+scales, bf16 cast, or full width).  tools/bandwidth.py and
        # bench.py --exchange read deltas of this to report measured
        # bytes-per-step, compressed vs fp32 (ISSUE 5 acceptance).
        self.wire_bytes = 0
        # whole-step-compiled accounting (ISSUE 7): a lax.scan window of N
        # training steps is ONE device-program launch — dispatch_count
        # grows by the window's launches (1, +1 for its host->device batch
        # transfer), never by N.  compiled_steps tracks the optimizer
        # steps those windows covered so tools/dispatch_count.py can
        # report dispatches-per-step < 1 in scan mode.
        self.compiled_step_windows = 0
        self.compiled_steps = 0

    def track(self, chunk) -> None:
        self._live.add(chunk)

    def count_dispatch(self, n: int = 1) -> None:
        """Note `n` device-program dispatches (hot path: one int add)."""
        self.dispatch_count += n

    def count_step_window(self, steps: int, dispatches: int = 1) -> None:
        """Note one compiled N-step window: `steps` optimizer steps
        executed under `dispatches` device launches (the window dispatch,
        plus any host->device input transfer the caller counts)."""
        self.dispatch_count += int(dispatches)
        self.compiled_step_windows += 1
        self.compiled_steps += int(steps)

    def count_wire_bytes(self, n: int) -> None:
        """Note `n` gradient-exchange wire bytes (hot path: one int add)."""
        self.wire_bytes += int(n)

    # -- engine type -------------------------------------------------------
    @property
    def kind(self) -> str:
        return get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")

    def is_naive(self) -> bool:
        # HOT PATH (called after every eager op): one raw os.environ read,
        # cached by VALUE — catches both set_env/environment() (which keep
        # os.environ in sync) and direct monkeypatch.setenv writes, without
        # get_env's lock + override-dict + dtype machinery per dispatch
        import os
        val = os.environ.get("MXNET_ENGINE_TYPE")  # mxlint: disable=env-var-registry
        if val != self._kind_raw:
            self._kind_raw = val
            self._naive = val in ("NaiveEngine", "naive")
        return self._naive

    # -- sync points -------------------------------------------------------
    def wait_for_var(self, value) -> None:
        """Block until `value` (a jax.Array) is computed (≈ WaitForVar)."""
        if value is not None and hasattr(value, "block_until_ready"):
            value.block_until_ready()

    def wait_for_all(self) -> None:
        """Reference: MXNDArrayWaitAll — block until every live array's
        pending computation (and any effects) completed; surfaces deferred
        device errors here, matching the reference's sync-point contract."""
        try:
            jax.effects_barrier()
        except Exception:
            pass
        for chunk in list(self._live):
            data = getattr(chunk, "data", None)
            if data is not None and hasattr(data, "block_until_ready"):
                # a buffer donated into a jit (e.g. parallel.TrainStep) is
                # deleted on the device; there is nothing left to wait on
                if getattr(data, "is_deleted", lambda: False)():
                    continue
                data.block_until_ready()

    def maybe_sync(self, value):
        """Called by the dispatch layer after every eager op."""
        if self.is_naive():
            self.wait_for_var(value)
        return value

    # -- bulking (no-op on TPU; XLA fuses) ---------------------------------
    def set_bulk_size(self, size: int) -> int:
        return 0

    def start_bulk(self):
        return None

    def stop_bulk(self):
        return None


engine = Engine()


def is_naive() -> bool:
    return engine.is_naive()


def wait_all() -> None:
    engine.wait_for_all()


def set_bulk_size(size: int) -> int:
    return engine.set_bulk_size(size)
