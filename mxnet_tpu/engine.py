"""Execution-engine semantics over XLA async dispatch.

Reference: src/engine/threaded_engine.cc (ThreadedEngine), naive_engine.cc
(NaiveEngine), include/mxnet/engine.h.

The reference schedules every op as a DAG node over read/write variable
dependencies and runs them on per-device worker threads.  On TPU, PJRT already
gives us exactly those semantics: op dispatch is async, data dependencies are
tracked by buffer definition events, and results only block at explicit sync
points.  What remains of the "engine" is therefore:

  * the sync surface — `wait_to_read` (= block_until_ready), `WaitForAll`;
  * a strict-sync debug mode (`MXNET_ENGINE_TYPE=NaiveEngine`) that blocks
    after every op, used to bisect async-scheduling bugs (SURVEY.md §5.2);
  * deferred-exception propagation: XLA raises device errors at sync points,
    matching the reference's capture-on-worker / rethrow-at-sync contract;
  * bulking (`Engine::StartBulk`): subsumed by XLA fusion — kept as no-ops.
"""
from __future__ import annotations

import jax

from .base import get_env

__all__ = ["Engine", "engine", "is_naive", "wait_all", "set_bulk_size"]


class Engine:
    """Process-wide engine facade (singleton, like Engine::Get())."""

    def __init__(self):
        import weakref
        from . import telemetry as _telemetry
        self._kind_raw = object()   # sentinel: never equals a str
        self._naive = False
        # live NDArray chunks, registered at creation/write; WaitForAll
        # blocks on each — the reference's "wait for all vars" semantics
        self._live = weakref.WeakSet()
        # ISSUE 8: the step accounting lives in the telemetry registry
        # (one source of truth for exposition, flight recorder and crash
        # dumps); the dispatch_count / wire_bytes / compiled_* properties
        # below alias it so tools/dispatch_count.py, tools/bandwidth.py
        # and every existing delta-reading harness keep working.
        #
        # - engine.dispatch_count: device-program launches since process
        #   start — eager op invokes, fused tree updates, kvstore
        #   collectives, metric accumulates, whole-graph jit steps.  The
        #   dispatch-budget harness pins O(#buckets)-dispatches-per-step
        #   on deltas of this.
        # - engine.wire_bytes: gradient-exchange payload bytes in their
        #   wire representation (compressed codes+scales, bf16 cast, or
        #   full width) — ISSUE 5 acceptance reads deltas.
        # - compiled windows/steps (ISSUE 7): a lax.scan window of N
        #   steps is ONE launch; compiled_steps attributes the N.
        self._c_dispatch = _telemetry.registry.counter(
            "engine.dispatch_count",
            doc="device-program dispatches since process start")
        self._c_wire = _telemetry.registry.counter(
            "engine.wire_bytes",
            doc="gradient-exchange wire bytes (compressed representation)")
        self._c_windows = _telemetry.registry.counter(
            "engine.compiled_step_windows",
            doc="whole-step-compiled window launches")
        self._c_steps = _telemetry.registry.counter(
            "engine.compiled_steps",
            doc="optimizer steps covered by compiled windows")
        # ISSUE 10: one mutation lock over the counter group so
        # snapshot() returns a CONSISTENT view — count_step_window bumps
        # three counters; a reader between the bumps used to see windows
        # advanced but steps not.  Order: _snap_lock -> counter leaf
        # lock, always (acyclic).
        import threading
        self._snap_lock = threading.Lock()

    def track(self, chunk) -> None:
        self._live.add(chunk)

    # -- telemetry-registry-backed counters (ISSUE 8) ----------------------
    # kept as read/write properties: harnesses read them as plain ints and
    # tests reset them with `engine.wire_bytes = 0`
    @property
    def dispatch_count(self) -> int:
        return self._c_dispatch.value

    @dispatch_count.setter
    def dispatch_count(self, v: int) -> None:
        with self._snap_lock:           # resets respect snapshot() too
            self._c_dispatch.set(int(v))

    @property
    def wire_bytes(self) -> int:
        return self._c_wire.value

    @wire_bytes.setter
    def wire_bytes(self, v: int) -> None:
        with self._snap_lock:
            self._c_wire.set(int(v))

    @property
    def compiled_step_windows(self) -> int:
        return self._c_windows.value

    @compiled_step_windows.setter
    def compiled_step_windows(self, v: int) -> None:
        with self._snap_lock:
            self._c_windows.set(int(v))

    @property
    def compiled_steps(self) -> int:
        return self._c_steps.value

    @compiled_steps.setter
    def compiled_steps(self, v: int) -> None:
        with self._snap_lock:
            self._c_steps.set(int(v))

    def count_dispatch(self, n: int = 1) -> None:
        """Note `n` device-program dispatches (hot path: one counter add)."""
        with self._snap_lock:
            self._c_dispatch.inc(n)

    def count_step_window(self, steps: int, dispatches: int = 1) -> None:
        """Note one compiled N-step window: `steps` optimizer steps
        executed under `dispatches` device launches (the window dispatch,
        plus any host->device input transfer the caller counts)."""
        with self._snap_lock:
            self._c_dispatch.inc(int(dispatches))
            self._c_windows.inc(1)
            self._c_steps.inc(int(steps))

    def count_wire_bytes(self, n: int) -> None:
        """Note `n` gradient-exchange wire bytes (hot path: one counter
        add)."""
        with self._snap_lock:
            self._c_wire.inc(int(n))

    def snapshot(self) -> dict:
        """ONE consistent view of the step-accounting counter group
        (ISSUE 10 satellite): dispatches, wire bytes, compiled windows/
        steps taken under the same mutation lock every count_* helper
        holds — bench/tools read this instead of several properties
        racily mid-step — plus the program-registry size."""
        with self._snap_lock:
            snap = {
                "dispatches": self._c_dispatch.value,
                "wire_bytes": self._c_wire.value,
                "compiled_step_windows": self._c_windows.value,
                "compiled_steps": self._c_steps.value,
            }
        from . import programs as _programs
        snap["programs"] = _programs.program_count()
        return snap

    # -- engine type -------------------------------------------------------
    @property
    def kind(self) -> str:
        return get_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")

    def is_naive(self) -> bool:
        # HOT PATH (called after every eager op): one raw os.environ read,
        # cached by VALUE — catches both set_env/environment() (which keep
        # os.environ in sync) and direct monkeypatch.setenv writes, without
        # get_env's lock + override-dict + dtype machinery per dispatch
        import os
        val = os.environ.get("MXNET_ENGINE_TYPE")  # mxlint: disable=env-var-registry
        if val != self._kind_raw:
            self._kind_raw = val
            self._naive = val in ("NaiveEngine", "naive")
        return self._naive

    # -- sync points -------------------------------------------------------
    def wait_for_var(self, value) -> None:
        """Block until `value` (a jax.Array) is computed (≈ WaitForVar)."""
        if value is not None and hasattr(value, "block_until_ready"):
            value.block_until_ready()

    def wait_for_all(self) -> None:
        """Reference: MXNDArrayWaitAll — block until every live array's
        pending computation (and any effects) completed; surfaces deferred
        device errors here, matching the reference's sync-point contract."""
        try:
            jax.effects_barrier()
        except Exception:
            pass
        for chunk in list(self._live):
            data = getattr(chunk, "data", None)
            if data is not None and hasattr(data, "block_until_ready"):
                # a buffer donated into a jit (e.g. parallel.TrainStep) is
                # deleted on the device; there is nothing left to wait on
                if getattr(data, "is_deleted", lambda: False)():
                    continue
                data.block_until_ready()

    def maybe_sync(self, value):
        """Called by the dispatch layer after every eager op."""
        if self.is_naive():
            self.wait_for_var(value)
        return value

    # -- bulking (no-op on TPU; XLA fuses) ---------------------------------
    def set_bulk_size(self, size: int) -> int:
        return 0

    def start_bulk(self):
        return None

    def stop_bulk(self):
        return None


engine = Engine()


def is_naive() -> bool:
    return engine.is_naive()


def wait_all() -> None:
    engine.wait_for_all()


def set_bulk_size(size: int) -> int:
    return engine.set_bulk_size(size)
