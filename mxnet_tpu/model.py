"""mx.model — checkpoint helpers, BatchEndParam and the deprecated
``FeedForward`` class.

Reference: ``python/mxnet/model.py`` (save_checkpoint, load_checkpoint,
BatchEndParam, class FeedForward).  FeedForward here is the same thin
deprecated veneer the reference ships: a Module wrapped in the v1.x
numpy-in/numpy-out convenience API, kept so classic scripts run
unmodified.

Artifact layout matches the reference exactly:
  ``prefix-symbol.json``   — Symbol.tojson()
  ``prefix-%04d.params``   — nd.save dict with ``arg:``/``aux:`` prefixes
so checkpoints interchange with reference tooling.
"""
from __future__ import annotations

import warnings
from collections import namedtuple
from typing import Dict, Tuple

import numpy as _np

from . import initializer as init_mod
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params", "FeedForward"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, remove_amp_cast: bool = True) -> None:
    """Reference: model.save_checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix: str, epoch: int) -> Tuple[Dict, Dict]:
    """Reference: model.load_params — just the two param dicts."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params: Dict[str, NDArray] = {}
    aux_params: Dict[str, NDArray] = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """Reference: model.load_checkpoint → (symbol, arg_params, aux_params).
    """
    from . import symbol as sym
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Deprecated v1.x estimator (reference: ``python/mxnet/model.py``
    class FeedForward).  A thin veneer over :class:`mxnet_tpu.module.Module`
    accepting numpy arrays / NDArrays / DataIters, kept for script
    compatibility; new code should use Module or Gluon."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=init_mod.Uniform(0.01),
                 numpy_batch_size=128, arg_params=None, aux_params=None,
                 allow_extra_params=False, begin_epoch=0, **kwargs):
        warnings.warn(
            "\033[91mmxnet_tpu.model.FeedForward has been deprecated. "
            "Please use mxnet_tpu.mod.Module instead.\033[0m",
            DeprecationWarning, stacklevel=2)
        from .device import cpu as _cpu
        self.symbol = symbol
        if ctx is None:
            ctx = [_cpu()]
        elif not isinstance(ctx, (list, tuple)):
            ctx = [ctx]
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        # reference: leftover kwargs are optimizer hyper-parameters
        self.kwargs = dict(kwargs)
        self._module = None

    # -- data plumbing (reference: model._init_data) ------------------------
    def _label_names(self):
        return [a for a in self.symbol.list_arguments()
                if a.endswith("_label")] or ["softmax_label"]

    def _as_iter(self, X, y=None, is_train=False):
        from .io import DataIter, NDArrayIter
        if isinstance(X, DataIter):
            return X
        if isinstance(X, NDArray):
            X = X.asnumpy()
        if y is not None and isinstance(y, NDArray):
            y = y.asnumpy()
        X = _np.asarray(X)
        if y is not None:
            y = _np.asarray(y)
        batch = min(self.numpy_batch_size, X.shape[0])
        label_name = self._label_names()[0]
        # reference _init_data trains with roll_over (padded head samples
        # must not get a second gradient/metric contribution per epoch)
        return NDArrayIter(X, y, batch_size=batch, shuffle=is_train,
                           label_name=label_name,
                           last_batch_handle="roll_over" if is_train
                           else "pad")

    def _create_module(self, it, for_training, logger=None):
        import logging as _logging
        from .module import Module
        label_names = tuple(self._label_names()) \
            if it.provide_label else ()
        data_names = tuple(d[0] if isinstance(d, (tuple, list)) else d.name
                           for d in it.provide_data)
        # the full ctx list goes through so Module can emit its
        # multi-device guidance (parallel.TrainStep) instead of a
        # silent device drop
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names,
                     context=self.ctx if len(self.ctx) > 1 else self.ctx[0],
                     logger=logger or _logging)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label if label_names else None,
                 for_training=for_training)
        mod.init_params(initializer=self.initializer,
                        arg_params=self.arg_params,
                        aux_params=self.aux_params,
                        allow_missing=self.arg_params is not None,
                        allow_extra=self.allow_extra_params)
        return mod

    # -- training -----------------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None, eval_end_callback=None,
            eval_batch_end_callback=None, checkpoint_dir=None,
            checkpoint_period=1, auto_resume=True):
        """Reference: FeedForward.fit — train on X/y (arrays or DataIter).

        ``checkpoint_dir``/``checkpoint_period``/``auto_resume`` pass
        through to :meth:`Module.fit`'s fault-tolerance hook: periodic
        crash-safe checkpointing with restart-from-latest resume.  The
        delegated loop also installs :class:`mxnet_tpu.health.StepGuard`
        from the environment, so ``MX_NAN_POLICY`` / ``MX_STEP_TIMEOUT``
        / ``MX_HEARTBEAT_FILE`` guard classic FeedForward scripts the
        same as Module ones."""
        data = self._as_iter(X, y, is_train=True)
        if self.epoch_size is not None:
            # reference: epoch_size bounds batches/epoch (the epoch
            # boundary for unbounded/streaming iterators)
            from .io import ResizeIter
            data = ResizeIter(data, self.epoch_size)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            # (X, y) tuple form
            eval_data = self._as_iter(eval_data[0], eval_data[1])
        # _create_module binds AND initializes (initializer/arg_params/
        # allow_extra handled there) — Module.fit's own bind/init_params
        # early-return on the already-prepared module, so the init args
        # are deliberately not re-passed
        self._module = self._create_module(data, for_training=True,
                                           logger=logger)
        opt_params = dict(self.kwargs)
        self._module.fit(
            data, eval_data=eval_data, eval_metric=eval_metric,
            epoch_end_callback=epoch_end_callback,
            batch_end_callback=batch_end_callback, kvstore=kvstore,
            optimizer=self.optimizer, optimizer_params=opt_params,
            eval_end_callback=eval_end_callback,
            eval_batch_end_callback=eval_batch_end_callback,
            begin_epoch=self.begin_epoch, num_epoch=self.num_epoch,
            monitor=monitor, checkpoint_dir=checkpoint_dir,
            checkpoint_period=checkpoint_period, auto_resume=auto_resume)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    # -- inference ----------------------------------------------------------
    def _inference_module(self, it):
        if self._module is None:
            assert self.arg_params is not None, \
                "model has not been trained or loaded"
            self._module = self._create_module(it, for_training=False)
        return self._module

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """Reference: FeedForward.predict — numpy out (list when the net
        has multiple outputs); with return_data, also (data, label)."""
        it = self._as_iter(X)
        mod = self._inference_module(it)
        if not return_data:
            # the batch loop / pad trimming / concatenation live in ONE
            # place: BaseModule.predict
            preds = mod.predict(it, num_batch=num_batch, reset=reset)
            if isinstance(preds, list):
                return [p.asnumpy() for p in preds]
            return preds.asnumpy()
        if reset:
            it.reset()
        outs, datas, labels = None, [], []
        for i, batch in enumerate(it):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            pad = getattr(batch, "pad", 0) or 0
            keep = batch.data[0].shape[0] - pad
            got = [o.asnumpy()[:keep] for o in mod.get_outputs()]
            if outs is None:
                outs = [[] for _ in got]
            for acc, o in zip(outs, got):
                acc.append(o)
            datas.append(batch.data[0].asnumpy()[:keep])
            labels.append(batch.label[0].asnumpy()[:keep]
                          if batch.label else None)
        preds = [_np.concatenate(o, axis=0) for o in (outs or [])]
        result = preds[0] if len(preds) == 1 else preds
        data_np = _np.concatenate(datas, axis=0)
        label_np = (None if not labels or labels[0] is None
                    else _np.concatenate(labels, axis=0))
        return result, data_np, label_np

    def score(self, X, y=None, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """Reference: FeedForward.score → the metric's scalar value.
        Accepts a label-carrying DataIter, or numpy/NDArray X with y."""
        from . import metric as metric_mod
        it = self._as_iter(X, y)
        assert it.provide_label, \
            "score needs labels: pass y, or a DataIter that provides them"
        mod = self._inference_module(it)
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        mod.score(it, eval_metric, num_batch=num_batch, reset=reset,
                  batch_end_callback=batch_end_callback)
        # reference returns eval_metric.get()[1]: a scalar for a simple
        # metric, the list of values for a composite
        return eval_metric.get()[1]

    # -- persistence (reference artifact layout) ----------------------------
    def save(self, prefix, epoch=None, remove_amp_cast=True):
        epoch = self.num_epoch if epoch is None else epoch
        assert epoch is not None, "epoch unknown: pass save(prefix, epoch)"
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {},
                        remove_amp_cast=remove_amp_cast)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """Reference: FeedForward.load — rebuild from a checkpoint."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               epoch_size=None, optimizer="sgd",
               initializer=init_mod.Uniform(0.01), eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Reference: FeedForward.create — construct + fit in one call."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
