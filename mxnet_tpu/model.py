"""mx.model — checkpoint helpers + BatchEndParam.

Reference: ``python/mxnet/model.py`` (save_checkpoint, load_checkpoint,
BatchEndParam; the FeedForward class itself is superseded by Module and
not rebuilt — SURVEY §1 L12).

Artifact layout matches the reference exactly:
  ``prefix-symbol.json``   — Symbol.tojson()
  ``prefix-%04d.params``   — nd.save dict with ``arg:``/``aux:`` prefixes
so checkpoints interchange with reference tooling.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Dict, Tuple

from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, remove_amp_cast: bool = True) -> None:
    """Reference: model.save_checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix: str, epoch: int) -> Tuple[Dict, Dict]:
    """Reference: model.load_params — just the two param dicts."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params: Dict[str, NDArray] = {}
    aux_params: Dict[str, NDArray] = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """Reference: model.load_checkpoint → (symbol, arg_params, aux_params).
    """
    from . import symbol as sym
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
