"""Sparse NDArrays: RowSparse and CSR.

Reference: ``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray, CSRNDArray,
row_sparse_array, csr_matrix, cast_storage, retain, sparse.dot),
``src/operator/tensor/cast_storage-inl.h`` (CastStorage),
``src/operator/tensor/dot-inl.h`` (DotCsrDnsDnsImpl),
``src/operator/optimizer_op.cc`` (rowsparse SGD/Adam — the lazy updates live
in ``ops/optimizer.py`` here).

TPU-first design (SURVEY.md sparse row): XLA has no sparse storage — the MXU
wants dense tiles — so sparse here is *semantics*, not a kernel library:

* a RowSparseNDArray is (indices, data-rows); converting to dense is one
  ``scatter``; every fixed-nnz computation (dot, retain, lazy optimizer
  update) is a jitted gather/scatter/segment_sum, which XLA lowers well.
* discovering nnz (dense → sparse) is *dynamic-shaped* and therefore a
  host-side eager step — exactly the reference's CastStorage sync point.
* the payoff is the same as the reference's: embedding-sized workloads touch
  only the rows a batch used (optimizer updates, kvstore row_sparse_pull),
  instead of materializing full-vocabulary gradients.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import numpy as _np
import jax
import jax.numpy as jnp

from ..device import Context, current_context
from ..base import MXNetError
from .ndarray import NDArray, array as _dense_array, from_jax

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "retain",
           "dot", "zeros", "empty", "array"]


# -- jitted fixed-nnz kernels -------------------------------------------------

@functools.partial(jax.jit, static_argnames=("shape",))
def _rsp_to_dense(data, indices, shape):
    return jnp.zeros(shape, data.dtype).at[indices].set(data)


@functools.partial(jax.jit, static_argnames=("shape",))
def _csr_to_dense(data, indices, indptr, shape):
    nnz = data.shape[0]
    row_ids = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    return jnp.zeros(shape, data.dtype).at[row_ids, indices].add(data)


@functools.partial(jax.jit, static_argnames=("n_rows",))
def _csr_dot_dns(data, indices, indptr, rhs, n_rows):
    """out[i, :] = sum_{nz in row i} data[nz] * rhs[col[nz], :]."""
    nnz = data.shape[0]
    row_ids = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    contrib = data[:, None] * rhs[indices]
    return jax.ops.segment_sum(contrib, row_ids, num_segments=n_rows)


@functools.partial(jax.jit, static_argnames=("n_cols",))
def _csr_t_dot_dns(data, indices, indptr, rhs, n_cols):
    """out[j, :] = sum_{nz with col j} data[nz] * rhs[row[nz], :]."""
    nnz = data.shape[0]
    row_ids = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    contrib = data[:, None] * rhs[row_ids]
    return jax.ops.segment_sum(contrib, indices, num_segments=n_cols)


@jax.jit
def _row_mask(x):
    return jnp.any(x.reshape(x.shape[0], -1) != 0, axis=1)


@jax.jit
def _retain_rows(data, indices, keep_ids):
    """Gather the kept subset: rows of `indices` present in `keep_ids`
    survive; absent keep_ids yield zero rows (reference retain semantics:
    the result's indices are exactly `keep_ids` ∩ stored, but with fixed
    shapes we return one row per keep_id, zeros where missing)."""
    pos = jnp.searchsorted(indices, keep_ids)
    pos = jnp.clip(pos, 0, indices.shape[0] - 1)
    hit = indices[pos] == keep_ids
    rows = data[pos]
    return jnp.where(hit[(...,) + (None,) * (data.ndim - 1)], rows,
                     jnp.zeros_like(rows)), hit


# -- classes ------------------------------------------------------------------

class BaseSparseNDArray:
    """Common surface shared by RowSparseNDArray / CSRNDArray."""

    stype: str = "undefined"

    def __init__(self, shape: Tuple[int, ...], dtype, ctx: Context):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = _np.dtype(dtype)
        self._ctx = ctx

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        out = 1
        for s in self._shape:
            out *= s
        return out

    def asnumpy(self):
        return self.tostype("default").asnumpy()

    def astype(self, dtype):
        raise NotImplementedError

    def tostype(self, stype):
        raise NotImplementedError

    def wait_to_read(self):
        pass

    def __len__(self):
        return self._shape[0]

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(str(s) for s in self._shape),
                                  self._ctx)


class RowSparseNDArray(BaseSparseNDArray):
    """A majority-zero-rows array: (indices, data) (reference:
    RowSparseNDArray — indices are the ids of non-zero rows, sorted
    ascending and unique; data stacks those rows)."""

    stype = "row_sparse"

    def __init__(self, data: NDArray, indices: NDArray,
                 shape: Tuple[int, ...]):
        if data.shape[0] != indices.shape[0]:
            raise ValueError("data rows (%d) != indices (%d)"
                             % (data.shape[0], indices.shape[0]))
        super().__init__(shape, data.dtype, data.context)
        self._data = data
        self._indices = indices

    @property
    def data(self) -> NDArray:
        return self._data

    @property
    def indices(self) -> NDArray:
        return self._indices

    def _assign(self, data: NDArray, indices: NDArray):
        """Replace contents in place, keeping class invariants (length
        match, declared dtype) — the mutation point kvstore uses."""
        if data.shape[0] != indices.shape[0]:
            raise ValueError("data rows (%d) != indices (%d)"
                             % (data.shape[0], indices.shape[0]))
        if data.dtype != self._dtype:
            data = data.astype(self._dtype)
        self._data = data
        self._indices = indices
        self._ctx = data.context

    def tostype(self, stype: str):
        if stype == "row_sparse":
            return self
        if stype == "default":
            if self._data.shape[0] == 0:
                return _dense_array(_np.zeros(self._shape, self._dtype),
                                    ctx=self._ctx)
            return from_jax(_rsp_to_dense(self._data._jax,
                                          self._indices._jax, self._shape),
                            ctx=self._ctx)
        raise ValueError("cannot cast row_sparse to %r" % stype)

    def astype(self, dtype):
        return RowSparseNDArray(self._data.astype(dtype), self._indices,
                                self._shape)

    def retain(self, row_ids):
        return retain(self, row_ids)

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            raise MXNetError("copyto(row_sparse): destination must be "
                             "rebuilt, arrays are (indices,data) pairs")
        return self.tostype("default").copyto(other)

    def __neg__(self):
        return RowSparseNDArray(-self._data, self._indices, self._shape)

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return RowSparseNDArray(self._data * other, self._indices,
                                    self._shape)
        return NotImplemented

    __rmul__ = __mul__


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row 2-D array (reference: CSRNDArray)."""

    stype = "csr"

    def __init__(self, data: NDArray, indices: NDArray, indptr: NDArray,
                 shape: Tuple[int, int]):
        if len(shape) != 2:
            raise ValueError("CSR must be 2-D, got %s" % (shape,))
        super().__init__(shape, data.dtype, data.context)
        self._data = data
        self._indices = indices
        self._indptr = indptr

    @property
    def data(self) -> NDArray:
        return self._data

    @property
    def indices(self) -> NDArray:
        return self._indices

    @property
    def indptr(self) -> NDArray:
        return self._indptr

    def tostype(self, stype: str):
        if stype == "csr":
            return self
        if stype == "default":
            if self._data.shape[0] == 0:
                return _dense_array(_np.zeros(self._shape, self._dtype),
                                    ctx=self._ctx)
            return from_jax(_csr_to_dense(self._data._jax,
                                          self._indices._jax,
                                          self._indptr._jax, self._shape),
                            ctx=self._ctx)
        raise ValueError("cannot cast csr to %r" % stype)

    def astype(self, dtype):
        return CSRNDArray(self._data.astype(dtype), self._indices,
                          self._indptr, self._shape)

    def copyto(self, other):
        return self.tostype("default").copyto(other)

    def __getitem__(self, i):
        # row slice returns a dense row (parity convenience, eager)
        return self.tostype("default")[i]


# -- constructors -------------------------------------------------------------

def _as_idx(x, ctx):
    if isinstance(x, NDArray):
        return x.astype(_np.int32) if x.dtype != _np.int32 else x
    return _dense_array(_np.asarray(x, _np.int32), ctx=ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray (reference: sparse.row_sparse_array).

    ``arg1`` is either a (data, indices) pair or a dense array-like (in
    which case zero rows are stripped — a host-side nnz discovery).
    """
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if not isinstance(data, NDArray):
            data = _dense_array(_np.asarray(data, dtype), ctx=ctx)
        elif dtype is not None:
            data = data.astype(dtype)
        indices = _as_idx(indices, ctx)
        if shape is None:
            raise ValueError("shape is required with (data, indices)")
        # class invariant: indices sorted ascending (retain/kvstore
        # searchsorted relies on it) — sort on device if needed
        idx_np = indices.asnumpy()
        if idx_np.size and _np.any(idx_np[1:] < idx_np[:-1]):
            order = jnp.argsort(indices._jax)
            indices = from_jax(indices._jax[order], ctx=ctx)
            data = from_jax(data._jax[order], ctx=ctx)
        return RowSparseNDArray(data, indices, tuple(shape))
    # dense input — nnz discovery syncs only a (rows,) bool mask to host;
    # the row gather stays on device (review finding: a full asnumpy() of
    # an embedding-sized gradient would negate the lazy-update payoff)
    if isinstance(arg1, NDArray):
        mask = _np.asarray(_row_mask(arg1._jax))
        nz = _np.flatnonzero(mask).astype(_np.int32)
        rows = arg1._jax[jnp.asarray(nz)]
        return RowSparseNDArray(from_jax(rows, ctx=arg1.context),
                                _dense_array(nz, ctx=arg1.context),
                                tuple(shape or arg1.shape))
    dense = _np.asarray(arg1, dtype)
    nz = _np.flatnonzero(dense.reshape(dense.shape[0], -1).any(axis=1))
    return RowSparseNDArray(
        _dense_array(dense[nz], ctx=ctx),
        _dense_array(nz.astype(_np.int32), ctx=ctx),
        tuple(shape or dense.shape))


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) or dense
    (reference: sparse.csr_matrix)."""
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if not isinstance(data, NDArray):
            data = _dense_array(_np.asarray(data, dtype), ctx=ctx)
        indices = _as_idx(indices, ctx)
        indptr = _as_idx(indptr, ctx)
        if shape is None:  # infer like the reference: rows from indptr,
            cols = int(indices.asnumpy().max()) + 1 if len(indices) else 0
            shape = (len(indptr) - 1, cols)
        return CSRNDArray(data, indices, indptr, tuple(shape))
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else \
        _np.asarray(arg1, dtype)
    if dense.ndim != 2:
        raise ValueError("csr_matrix needs 2-D input")
    rows, cols = _np.nonzero(dense)
    data = dense[rows, cols]
    indptr = _np.zeros(dense.shape[0] + 1, _np.int32)
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr).astype(_np.int32)
    return CSRNDArray(_dense_array(data, ctx=ctx),
                      _dense_array(cols.astype(_np.int32), ctx=ctx),
                      _dense_array(indptr, ctx=ctx),
                      tuple(shape or dense.shape))


def zeros(stype: str, shape, ctx=None, dtype="float32"):
    """All-zero sparse array (reference: sparse.zeros)."""
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    dt = _np.dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(
            _dense_array(_np.zeros((0,) + shape[1:], dt), ctx=ctx),
            _dense_array(_np.zeros((0,), _np.int32), ctx=ctx), shape)
    if stype == "csr":
        return CSRNDArray(
            _dense_array(_np.zeros((0,), dt), ctx=ctx),
            _dense_array(_np.zeros((0,), _np.int32), ctx=ctx),
            _dense_array(_np.zeros(shape[0] + 1, _np.int32), ctx=ctx), shape)
    if stype == "default":
        from . import ndarray as _nd
        return _nd.zeros(shape, ctx=ctx, dtype=dtype)
    raise ValueError("unknown stype %r" % stype)


empty = zeros


def array(source, ctx=None, dtype=None):
    """Sparse-aware array(): passes sparse inputs through, converts
    scipy.sparse csr if available (reference: sparse.array)."""
    if isinstance(source, BaseSparseNDArray):
        return source
    if hasattr(source, "tocsr"):  # scipy.sparse matrix without importing scipy
        csr = source.tocsr()
        return csr_matrix((csr.data, csr.indices, csr.indptr),
                          shape=csr.shape, ctx=ctx, dtype=dtype)
    return csr_matrix(source, ctx=ctx, dtype=dtype)


# -- functional surface -------------------------------------------------------

def cast_storage(arr, stype: str):
    """Convert between storage types (reference: cast_storage op).

    dense→sparse discovers nnz on the host (a sync point, as in the
    reference); sparse→dense is a jitted scatter.
    """
    if isinstance(arr, BaseSparseNDArray):
        if stype == "default":
            return arr.tostype("default")
        if stype == arr.stype:
            return arr
        return cast_storage(arr.tostype("default"), stype)
    if stype == "default":
        return arr
    if stype == "row_sparse":
        return row_sparse_array(arr, shape=arr.shape, ctx=arr.context)
    if stype == "csr":
        return csr_matrix(arr, shape=arr.shape, ctx=arr.context)
    raise ValueError("unknown stype %r" % stype)


def retain(rsp: RowSparseNDArray, row_ids) -> RowSparseNDArray:
    """Keep only `row_ids` rows (reference: sparse.retain — the kvstore
    row_sparse_pull building block)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    ids = _as_idx(row_ids, rsp.context)
    if rsp._data.shape[0] == 0:
        data = _dense_array(
            _np.zeros((ids.shape[0],) + rsp.shape[1:], rsp.dtype),
            ctx=rsp.context)
        return RowSparseNDArray(data, ids, rsp.shape)
    rows, _hit = _retain_rows(rsp._data._jax, rsp._indices._jax, ids._jax)
    return RowSparseNDArray(from_jax(rows, ctx=rsp.context), ids, rsp.shape)


def dot(lhs, rhs, transpose_a: bool = False, transpose_b: bool = False):
    """Sparse dot (reference: src/operator/tensor/dot-inl.h).

    Supported: dot(csr, dense), dot(csr.T, dense) — the fwd/bwd pair of
    sparse-input linear layers.  Dense×dense falls through to nd.dot.
    """
    if transpose_b:
        raise NotImplementedError("sparse dot with transpose_b")
    if isinstance(lhs, CSRNDArray):
        rhs_jax = rhs._jax if isinstance(rhs, NDArray) else jnp.asarray(rhs)
        if transpose_a:
            out = _csr_t_dot_dns(lhs._data._jax, lhs._indices._jax,
                                 lhs._indptr._jax, rhs_jax, lhs.shape[1])
        else:
            out = _csr_dot_dns(lhs._data._jax, lhs._indices._jax,
                               lhs._indptr._jax, rhs_jax, lhs.shape[0])
        return from_jax(out, ctx=lhs.context)
    if isinstance(lhs, RowSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        raise NotImplementedError(
            "sparse dot supports csr×dense; densify with tostype('default')")
    from .ndarray import invoke
    return invoke("dot", lhs, rhs, transpose_a=transpose_a,
                  transpose_b=transpose_b)
