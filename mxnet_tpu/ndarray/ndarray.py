"""NDArray: the imperative tensor.

Reference: include/mxnet/ndarray.h (class NDArray), src/ndarray/ndarray.cc
(CopyFromTo, NDArray::Save/Load), python/mxnet/ndarray/ndarray.py
(class NDArray, asnumpy, attach_grad, __getitem__).

TPU-native design
-----------------
The reference NDArray is a ref-counted chunk of device memory plus an engine
variable used for async dependency tracking.  Here the chunk holds a
``jax.Array`` (a PJRT HBM buffer): dispatch is async by construction, the
engine variable's role is played by the buffer's definition event, and
``wait_to_read`` is ``block_until_ready`` (SURVEY.md §3.2 TPU mapping).

Mutability over an immutable substrate: MXNet NDArrays are mutable
(``a[:] = x``, fused optimizer updates write weights in place) and slices are
*views* that write through to their base.  We keep a mutable ``_Chunk`` cell
holding the current jax.Array; in-place writes functionally update the root
array (``data.at[idx].set(v)``) and swap the cell.  Views record their basic
index into the root chunk and read/write through it.  A version counter on the
chunk lets views cache their materialized value.
"""
from __future__ import annotations

import numbers
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError, get_env
from ..device import Context, current_context, cpu
from ..engine import engine
from ..ops.registry import get_op, cached_jit
from .. import profiler as _profiler
from .. import amp as _amp

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "zeros_like", "ones_like", "concatenate", "stack_arrays",
           "save", "load", "save_bytes", "load_bytes", "waitall",
           "from_jax", "DTYPE_TO_FLAG", "FLAG_TO_DTYPE"]

# mshadow type flags (3rdparty/mshadow/mshadow/base.h TypeFlag)
DTYPE_TO_FLAG = {
    _np.dtype("float32"): 0, _np.dtype("float64"): 1, _np.dtype("float16"): 2,
    _np.dtype("uint8"): 3, _np.dtype("int32"): 4, _np.dtype("int8"): 5,
    _np.dtype("int64"): 6, _np.dtype("bool"): 7, _np.dtype("int16"): 8,
    _np.dtype("uint16"): 9, _np.dtype("uint32"): 10, _np.dtype("uint64"): 11,
    _np.dtype(jnp.bfloat16): 12,
}
FLAG_TO_DTYPE = {v: k for k, v in DTYPE_TO_FLAG.items()}


def _default_dtype():
    return _np.dtype(get_env("MXNET_DEFAULT_DTYPE", "float32"))


class _Chunk:
    """Mutable cell holding the current root jax.Array + a write version."""
    __slots__ = ("data", "version", "ctx", "__weakref__")

    def __init__(self, data: jax.Array, ctx: Context):
        self.data = data
        self.version = 0
        self.ctx = ctx
        # concrete arrays only — tracers (hybridize/jit trace time) must not
        # leak into the engine's live set
        if isinstance(data, jax.Array) and not isinstance(data, jax.core.Tracer):
            engine.track(self)

    def write(self, new_data: jax.Array) -> None:
        self.data = new_data
        self.version += 1


def _put(value, ctx: Context) -> jax.Array:
    return jax.device_put(value, ctx.jax_device)


class NDArray:
    __slots__ = ("_chunk", "_index", "_vshape", "_cached", "_cached_version",
                 "_grad", "_grad_req", "_ag_node", "_grad_hook",
                 "__weakref__")

    # higher than numpy's so ndarray.__op__(numpy) defers to us
    __array_priority__ = 1000.0

    def __init__(self, data: jax.Array, ctx: Optional[Context] = None,
                 _chunk: Optional[_Chunk] = None, _index=None, _vshape=None):
        if _chunk is not None:
            self._chunk = _chunk
        else:
            ctx = ctx or current_context()
            self._chunk = _Chunk(data, ctx)
        self._index = _index          # basic index into root chunk, or None
        self._vshape = _vshape        # reshape-view target shape, or None
        self._cached = None
        self._cached_version = -1
        self._grad: Optional[NDArray] = None
        self._grad_req: str = "null"
        self._ag_node = None          # autograd tape node that produced this
        # overlap scheduling (ISSUE 5): set on a GRAD buffer, called the
        # moment backward finalizes its value — lets the Trainer launch a
        # fusion bucket's exchange mid-backward
        self._grad_hook = None

    # ------------------------------------------------------------------
    # raw value access
    # ------------------------------------------------------------------
    @property
    def _jax(self) -> jax.Array:
        ch = self._chunk
        if self._index is None and self._vshape is None:
            return ch.data
        if self._cached_version == ch.version and self._cached is not None:
            return self._cached
        val = ch.data
        if self._index is not None:
            val = val[self._index]
        if self._vshape is not None:
            val = val.reshape(self._vshape)
        self._cached = val
        self._cached_version = ch.version
        return val

    def _set_jax(self, value: jax.Array) -> None:
        """Whole-array in-place write (the `a[:] = x` / optimizer path)."""
        ch = self._chunk
        if self._index is None and self._vshape is None:
            ch.write(value)
        elif self._index is not None and self._vshape is None:
            ch.write(ch.data.at[self._index].set(value))
        else:  # reshape view of root
            ch.write(value.reshape(ch.data.shape).astype(ch.data.dtype))
        engine.maybe_sync(ch.data)

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._jax.shape)

    @property
    def dtype(self):
        return _np.dtype(self._jax.dtype)

    @property
    def size(self) -> int:
        return int(self._jax.size)

    @property
    def ndim(self) -> int:
        return self._jax.ndim

    @property
    def context(self) -> Context:
        return self._chunk.ctx

    ctx = context
    device = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return invoke("transpose", self)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(self.asscalar())

    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = _np.array2string(arr, precision=4, threshold=20)
        except Exception as e:  # async error surfaces here, like the reference
            body = "<unreadable: %s>" % e
        return "%s\n<NDArray %s @%s>" % (
            body, "x".join(str(d) for d in self.shape), self.context)

    # ------------------------------------------------------------------
    # sync / host transfer
    # ------------------------------------------------------------------
    def wait_to_read(self) -> None:
        engine.wait_for_var(self._jax)

    def wait_to_write(self) -> None:
        engine.wait_for_var(self._chunk.data)

    def asnumpy(self) -> _np.ndarray:
        """Sync point: device→host copy (reference: NDArray.asnumpy)."""
        return _np.asarray(self._jax)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        if self.ndim == 0 or self.size == 1:
            return int(self.asscalar())
        raise TypeError("only integer scalar arrays can be converted to index")

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    # numpy interop protocols (reference: mx.np.ndarray implements
    # __array_ufunc__/__array_function__ so numpy-API code operates on
    # MXNet arrays without a host copy): route numpy ufuncs/functions onto
    # the jnp implementations, returning NDArray — device-resident.
    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__" or kwargs:
            # reductions / dtype= / where= / casting= have numpy semantics
            # we don't replicate on device: run them on HOST numpy (the
            # pre-protocol __array__ behavior; NotImplemented would raise)
            vals = [x.asnumpy() if isinstance(x, NDArray) else x
                    for x in inputs]
            return getattr(ufunc, method)(*vals, **kwargs)
        from .. import numpy as _mxnp
        # prefer the mx.np implementation: registry-backed ops there go
        # through invoke(), so the call RECORDS on the autograd tape
        impl = getattr(_mxnp, ufunc.__name__, None)
        if impl is not None and callable(impl):
            try:
                return impl(*inputs)
            except (TypeError, MXNetError):
                pass
        jfn = getattr(jnp, ufunc.__name__, None)
        if jfn is None:
            return NotImplemented
        vals = [x._jax if isinstance(x, NDArray) else x for x in inputs]
        try:
            out = jfn(*vals)
        except TypeError:
            return NotImplemented
        if isinstance(out, tuple):
            return tuple(NDArray(o, ctx=self.context) for o in out)
        return NDArray(out, ctx=self.context)

    def __array_function__(self, func, types, args, kwargs):
        from .. import numpy as _mxnp
        impl = getattr(_mxnp, func.__name__, None)
        if impl is not None and callable(impl):
            try:
                return impl(*args, **kwargs)
            except (TypeError, MXNetError):
                pass  # numpy-only kwargs (where=, ...) -> host fallback

        # no device implementation: preserve the pre-protocol behavior by
        # coercing to host numpy (the __array__ fallback numpy used before
        # __array_function__ existed on this type)
        def coerce(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, (list, tuple)):
                return type(x)(coerce(v) for v in x)
            return x
        return func(*[coerce(a) for a in args],
                    **{k: coerce(v) for k, v in kwargs.items()})

    # pickling (reference: NDArray is picklable via its binary serialization;
    # used by Trainer.save_states / kvstore set_optimizer)
    def __reduce__(self):
        return (_unpickle_ndarray, (self.asnumpy(), str(self.dtype)
                                    if self.dtype != jnp.bfloat16 else
                                    "bfloat16"))

    # dlpack bridge (reference: NDArray::ToDLPack / FromDLPack)
    def __dlpack__(self, stream=None):
        return self._jax.__dlpack__()

    def __dlpack_device__(self):
        return self._jax.__dlpack_device__()

    # ------------------------------------------------------------------
    # copies / context movement
    # ------------------------------------------------------------------
    def copy(self) -> "NDArray":
        return NDArray(jnp.copy(self._jax), ctx=self.context)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        """Reference: CopyFromTo — cross-device copy through the engine."""
        if isinstance(other, Context):
            return NDArray(_put(self._jax, other), ctx=other)
        if not isinstance(other, NDArray):
            raise TypeError("copyto expects NDArray or Context")
        if other.context == self.context:
            # same device: device_put is a no-op and a same-dtype astype
            # returns an Array SHARING this buffer — copyto must produce
            # an independent value (the whole-step compiled lane donates
            # parameter buffers; an alias would be deleted with them)
            val = self._jax.astype(other.dtype) \
                if other.dtype != self.dtype else jnp.copy(self._jax)
        else:
            val = _put(self._jax, other.context).astype(other.dtype)
        other._set_jax(val)
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dtype = _np.dtype(jnp.bfloat16) if dtype in ("bfloat16", jnp.bfloat16) \
            else _np.dtype(dtype)
        if not copy and self.dtype == dtype:
            return self
        return invoke("cast", self, dtype=str(dtype) if dtype != jnp.bfloat16 else "bfloat16")

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # autograd surface (reference: attach_grad / .grad / detach / backward)
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None) -> None:
        from .. import autograd
        self._grad = NDArray(jnp.zeros(self.shape, self.dtype), ctx=self.context)
        self._grad_req = grad_req
        self._ag_node = autograd.VariableNode(self)

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    def detach(self) -> "NDArray":
        out = NDArray(None, _chunk=self._chunk, _index=self._index,
                      _vshape=self._vshape)
        return out

    def backward(self, out_grad: Optional["NDArray"] = None,
                 retain_graph: bool = False, train_mode: bool = True) -> None:
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    @staticmethod
    def _is_basic_index(key) -> bool:
        if isinstance(key, tuple):
            return all(isinstance(k, (slice, numbers.Integral)) or k is None
                       or k is Ellipsis for k in key)
        return isinstance(key, (slice, numbers.Integral)) or key is None \
            or key is Ellipsis

    def _unwrap_key(self, key):
        def conv(k):
            if isinstance(k, NDArray):
                return k._jax
            return k
        if isinstance(key, tuple):
            return tuple(conv(k) for k in key)
        return conv(key)

    def _check_bounds(self, key) -> None:
        """Basic integer indices must bound-check eagerly: JAX clamps, but
        MXNet (and Python's iteration protocol) require IndexError."""
        ks = key if isinstance(key, tuple) else (key,)
        axis = 0
        shape = self.shape
        for k in ks:
            if k is Ellipsis:
                axis = len(shape) - (len([x for x in ks if x is not None]) -
                                     ks.index(k) - 1)
                continue
            if k is None:
                continue
            if isinstance(k, numbers.Integral):
                if axis >= len(shape):
                    raise IndexError("too many indices for array")
                n = shape[axis]
                if not (-n <= int(k) < n):
                    raise IndexError(
                        "index %d is out of bounds for axis %d with size %d"
                        % (k, axis, n))
            axis += 1

    def __getitem__(self, key) -> "NDArray":
        key = self._unwrap_key(key)
        from .. import autograd
        if autograd.is_recording() and self._ag_node is not None and \
                self._is_basic_index(key):
            # recorded copy: keeps the gradient chain (views carry no node)
            self._check_bounds(key)
            return invoke("_internal_getitem", self,
                          key=key if isinstance(key, tuple) else (key,))
        if self._is_basic_index(key) and self._vshape is None:
            self._check_bounds(key)
            # view sharing the chunk: writes through (MXNet slice semantics)
            if self._index is None:
                new_index = key if isinstance(key, tuple) else (key,)
            else:
                # compose: slice the already-sliced region lazily by chaining.
                # We store a chained index as a nested marker.
                new_index = _compose_index(self._chunk.data.shape,
                                           self._index,
                                           key if isinstance(key, tuple) else (key,))
                if new_index is None:   # composition not expressible: copy
                    return NDArray(self._jax[key], ctx=self.context)
            out = NDArray(None, _chunk=self._chunk, _index=new_index)
            # basic indexing with out-of-range -> let jax/numpy semantics apply
            _ = out.shape
            return out
        # advanced indexing returns a copy (same as the reference)
        val = self._jax[key]
        return NDArray(val, ctx=self.context)

    def __setitem__(self, key, value) -> None:
        key = self._unwrap_key(key)
        if isinstance(value, NDArray):
            value = value._jax
        elif isinstance(value, (numbers.Number, _np.ndarray, list, tuple)):
            value = jnp.asarray(value, dtype=self.dtype)
        ch = self._chunk
        full_write = (key == slice(None)) or (
            isinstance(key, tuple) and all(k == slice(None) for k in key))
        if self._index is None and self._vshape is None:
            if full_write:
                ch.write(jnp.broadcast_to(value, self.shape).astype(self.dtype)
                         if getattr(value, "shape", None) != self.shape
                         or value.dtype != self.dtype else value)
            else:
                ch.write(ch.data.at[key].set(value))
        else:
            # view: read-modify-write through the root chunk
            sub = self._jax
            sub = sub.at[key].set(value) if not full_write else \
                jnp.broadcast_to(value, sub.shape).astype(sub.dtype)
            if self._vshape is not None:
                ch.write(sub.reshape(ch.data.shape).astype(ch.data.dtype))
            else:
                ch.write(ch.data.at[self._index].set(sub))
        self._cached = None
        engine.maybe_sync(ch.data)

    # ------------------------------------------------------------------
    # reshape view
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        shape = _infer_reshape(self.shape, shape)
        from .. import autograd
        if autograd.is_recording() and self._ag_node is not None:
            # recorded op-form reshape: a view would drop the tape node and
            # silently cut the gradient chain (rnn param packing relies on
            # grads flowing through reshape)
            return invoke("reshape", self, shape=shape)
        if self._index is None and self._vshape is None:
            # view of the root chunk: writes through (reference semantics)
            return NDArray(None, _chunk=self._chunk, _vshape=shape)
        return NDArray(self._jax.reshape(shape), ctx=self.context)

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return self.reshape(other.shape)

    # ------------------------------------------------------------------
    # arithmetic operators — all dispatch through the op registry so that
    # autograd records them uniformly
    # ------------------------------------------------------------------
    def _binop(self, name, other, reverse=False):
        if isinstance(other, numbers.Number):
            other = full((), other, ctx=self.context, dtype=self.dtype)
        elif isinstance(other, (_np.ndarray, list, tuple)):
            other = array(other, ctx=self.context)
        if not isinstance(other, NDArray):
            return NotImplemented
        return invoke(name, other, self) if reverse else invoke(name, self, other)

    def __add__(self, o):  return self._binop("broadcast_add", o)
    def __radd__(self, o): return self._binop("broadcast_add", o, True)
    def __sub__(self, o):  return self._binop("broadcast_sub", o)
    def __rsub__(self, o): return self._binop("broadcast_sub", o, True)
    def __mul__(self, o):  return self._binop("broadcast_mul", o)
    def __rmul__(self, o): return self._binop("broadcast_mul", o, True)
    def __truediv__(self, o):  return self._binop("broadcast_div", o)
    def __rtruediv__(self, o): return self._binop("broadcast_div", o, True)
    def __mod__(self, o):  return self._binop("broadcast_mod", o)
    def __rmod__(self, o): return self._binop("broadcast_mod", o, True)
    def __pow__(self, o):  return self._binop("broadcast_power", o)
    def __rpow__(self, o): return self._binop("broadcast_power", o, True)
    def __matmul__(self, o): return invoke("dot", self, o)
    def __neg__(self): return invoke("negative", self)
    def __abs__(self): return invoke("abs", self)

    # comparisons: legacy mx.nd returns float32 0/1; under npx.set_np()
    # they switch to the _npi numpy-semantics ops (bool outputs, so
    # x[x > 0] boolean masking works) — the reference's set_np contract
    @staticmethod
    def _cmp_op(legacy, npi):
        from .. import npx as _npx
        return npi if _npx.is_np_array() else legacy

    def __eq__(self, o):
        if o is None:
            return False
        return self._binop(self._cmp_op("broadcast_equal", "_npi_equal"), o)
    def __ne__(self, o):
        if o is None:
            return True
        return self._binop(self._cmp_op("broadcast_not_equal",
                                        "_npi_not_equal"), o)
    def __gt__(self, o):
        return self._binop(self._cmp_op("broadcast_greater",
                                        "_npi_greater"), o)
    def __ge__(self, o):
        return self._binop(self._cmp_op("broadcast_greater_equal",
                                        "_npi_greater_equal"), o)
    def __lt__(self, o):
        return self._binop(self._cmp_op("broadcast_lesser", "_npi_less"), o)
    def __le__(self, o):
        return self._binop(self._cmp_op("broadcast_lesser_equal",
                                        "_npi_less_equal"), o)

    def __hash__(self):
        return id(self)

    # in-place ops write through the chunk
    def _ibinop(self, name, other):
        res = self._binop(name, other)
        if res is NotImplemented:
            return res
        self._set_jax(res._jax.astype(self.dtype))
        return self

    def __iadd__(self, o): return self._ibinop("broadcast_add", o)
    def __isub__(self, o): return self._ibinop("broadcast_sub", o)
    def __imul__(self, o): return self._ibinop("broadcast_mul", o)
    def __itruediv__(self, o): return self._ibinop("broadcast_div", o)

    # ------------------------------------------------------------------
    # method forms of common ops (generated namespace adds the rest)
    # ------------------------------------------------------------------
    def sum(self, axis=None, keepdims=False, **kw):
        return invoke("sum", self, axis=_norm_axis(axis), keepdims=keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke("mean", self, axis=_norm_axis(axis), keepdims=keepdims)

    def max(self, axis=None, keepdims=False, **kw):
        return invoke("max", self, axis=_norm_axis(axis), keepdims=keepdims)

    def min(self, axis=None, keepdims=False, **kw):
        return invoke("min", self, axis=_norm_axis(axis), keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", self, axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", self, axis=axis, keepdims=keepdims)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke("transpose", self, axes=axes if axes else None)

    def flatten(self):
        return invoke("flatten", self)

    def expand_dims(self, axis):
        return invoke("expand_dims", self, axis=axis)

    def squeeze(self, axis=None):
        return invoke("squeeze", self, axis=axis)

    def broadcast_to(self, shape):
        return invoke("broadcast_to", self, shape=tuple(shape))

    def broadcast_like(self, other):
        return invoke("broadcast_to", self, shape=other.shape)

    def clip(self, a_min=None, a_max=None):
        return invoke("clip", self, a_min=a_min, a_max=a_max)

    def abs(self):
        return invoke("abs", self)

    def sqrt(self):
        return invoke("sqrt", self)

    def exp(self):
        return invoke("exp", self)

    def log(self):
        return invoke("log", self)

    def relu(self):
        return invoke("relu", self)

    def sigmoid(self):
        return invoke("sigmoid", self)

    def tanh(self):
        return invoke("tanh", self)

    def softmax(self, axis=-1):
        return invoke("softmax", self, axis=axis)

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", self, axis=axis)

    def dot(self, other):
        return invoke("dot", self, other)

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", self, indices, axis=axis, mode=mode)

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return invoke("one_hot", self, depth=depth, on_value=on_value,
                      off_value=off_value, dtype=dtype)

    def tostype(self, stype):
        if stype != "default":
            from .sparse import cast_storage
            return cast_storage(self, stype)
        return self

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", self, axis=axis, begin=begin, end=end)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("split", self, num_outputs=num_outputs, axis=axis,
                      squeeze_axis=squeeze_axis)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", self, ord=ord, axis=_norm_axis(axis), keepdims=keepdims)

    def save(self, fname: str):
        save(fname, self)


# ---------------------------------------------------------------------------
# index composition for chained basic views
# ---------------------------------------------------------------------------

def _expand_index(shape, idx):
    """Expand an index tuple to one entry per axis of `shape` (no newaxis)."""
    idx = list(idx)
    if Ellipsis in idx:
        pos = idx.index(Ellipsis)
        n_missing = len(shape) - (len(idx) - 1 - sum(1 for k in idx if k is None))
        idx[pos:pos + 1] = [slice(None)] * (n_missing)
    while len([k for k in idx if k is not None]) < len(shape):
        idx.append(slice(None))
    return idx


def _compose_index(root_shape, outer, inner):
    """Compose two basic indices: root[outer][inner] == root[composed].
    Returns None when not expressible as a single basic index."""
    if any(k is None for k in list(outer) + list(inner)):
        return None
    outer = _expand_index(root_shape, outer)
    # shape after outer
    inter_axes = []  # (root_axis, slice) for surviving axes
    for ax, k in enumerate(outer):
        if isinstance(k, slice):
            inter_axes.append((ax, k))
    inner = _expand_index(tuple(len(range(*k.indices(root_shape[ax])))
                                for ax, k in inter_axes), inner)
    if len(inner) > len(inter_axes):
        return None
    composed = list(outer)
    for (ax, sl), k in zip(inter_axes, inner):
        start, stop, step = sl.indices(root_shape[ax])
        n = len(range(start, stop, step))
        if isinstance(k, numbers.Integral):
            kk = int(k)
            if kk < 0:
                kk += n
            if not (0 <= kk < n):
                raise IndexError("index %d out of bounds for axis %d with size %d"
                                 % (k, ax, n))
            composed[ax] = start + kk * step
        elif isinstance(k, slice):
            s2, e2, st2 = k.indices(n)
            new_start = start + s2 * step
            new_step = step * st2
            cnt = len(range(s2, e2, st2))
            new_stop = new_start + cnt * new_step
            if new_step < 0 and new_stop < 0:
                new_stop = None
            composed[ax] = slice(new_start, new_stop, new_step)
        else:
            return None
    return tuple(composed)


def _infer_reshape(old_shape, new_shape):
    """MXNet reshape special codes: 0 (keep), -1 (infer), -2.. not supported."""
    out = []
    for i, d in enumerate(new_shape):
        if d == 0:
            out.append(old_shape[i])
        else:
            out.append(int(d))
    if out.count(-1) > 1:
        raise ValueError("can only specify one unknown dimension")
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in old_shape:
            total *= d
        out[out.index(-1)] = total // max(known, 1)
    return tuple(out)


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


# ---------------------------------------------------------------------------
# eager dispatch (reference: MXImperativeInvokeEx -> Imperative::Invoke)
# ---------------------------------------------------------------------------

# Symbol-trace hook (set by mxnet_tpu.symbol.trace_block): when non-None,
# every invoke() is also recorded as a graph node — the imperative run IS
# the trace (reference: hybrid_forward Symbol-proxy tracing).
_sym_tracer = None
_autograd = None


def invoke(op_name: str, *inputs, out=None, **params):
    # positional-attr extraction happens HERE, before dispatch AND before
    # the symbol tracer records — both must see the canonical call
    inputs = get_op(op_name).split_pos_attrs(inputs, params, NDArray)
    if _profiler.IMPERATIVE:
        with _profiler.op_span(op_name):
            ret = _invoke_impl(op_name, *inputs, out=out, **params)
            if _profiler.want_sync():
                jax.tree_util.tree_map(
                    lambda x: jax.block_until_ready(x._jax)
                    if isinstance(x, NDArray) else x, ret)
    else:
        ret = _invoke_impl(op_name, *inputs, out=out, **params)
    tracer = _sym_tracer
    if tracer is not None:
        tracer.record(op_name,
                      {k: v for k, v in params.items()
                       if k not in ("ctx", "name")},
                      inputs, ret)
    return ret


def _invoke_impl(op_name: str, *inputs, out=None, **params):
    """Invoke a registered op on NDArrays (HOT LOOP 1, SURVEY.md §3.2).

    - unwraps inputs to jax.Arrays (committed to their context's device)
    - if autograd is recording and the op is differentiable, routes through
      the tape (jax.vjp captures the backward closure);
    - otherwise calls the per-(op, params) jit-cached executable.
    """
    op = get_op(op_name)
    engine.count_dispatch()
    # MXNet op calls accept ctx= (output placement) and name= (symbol compat)
    ctx_kw = params.pop("ctx", None)
    params.pop("name", None)
    jax_in: List[jax.Array] = []
    ctx = ctx_kw
    for x in inputs:
        if isinstance(x, NDArray):
            jax_in.append(x._jax)
            if ctx is None:
                ctx = x.context
        elif isinstance(x, (numbers.Number, _np.ndarray, jnp.ndarray)):
            jax_in.append(jnp.asarray(x))
        elif x is None:
            jax_in.append(None)
        elif hasattr(x, "stype") and hasattr(x, "tostype"):
            # sparse input.  no_jit ops (graph/sampling ops) take the sparse
            # object raw; everything else gets the reference's storage
            # FALLBACK semantics — densify with a one-time warning
            # (src/operator/elemwise_op_common.h dispatch-fallback +
            # "storage fallback" LogStorageFallback).
            if op.no_jit:
                jax_in.append(x)
            else:
                _warn_storage_fallback(op_name, x.stype)
                jax_in.append(x.tostype("default")._jax)
            if ctx is None:
                ctx = x.context
        else:
            raise TypeError("invoke(%s): bad input type %s" % (op_name, type(x)))
    ctx = ctx or current_context()
    amp_state = _amp.current_state()
    if amp_state is not None:
        jax_in = amp_state.cast_inputs(op.name, params, jax_in)
    if op.needs_rng:
        from ..ops import random as _rnd
        jax_in.insert(0, _rnd.next_key())

    global _autograd
    if _autograd is None:
        from .. import autograd as _autograd  # lazy: breaks import cycle
    autograd = _autograd
    if autograd.is_recording() and op.differentiable:
        outs = autograd.record_op(op, params, inputs, jax_in, ctx)
    elif op.no_jit:
        # dynamic-output-shape op: eager only, outside the jit cache
        outs = op.fn(*jax_in, **params)
        outs = _wrap_outputs(op, outs, ctx)
    else:
        fn = cached_jit(op.name, params)
        outs = fn(*jax_in)
        if ctx_kw is not None:
            outs = jax.tree_util.tree_map(lambda o: _put(o, ctx_kw), outs)
        outs = _wrap_outputs(op, outs, ctx)
    # aux-state write-back (BatchNorm moving stats ≈ reference aux arrays):
    # designated outputs are stored into their input NDArrays in place and
    # stripped from the visible return
    # aux_writeback may be a callable of the call params for ops with a
    # variable arity (multi_sgd fleets: the output->input map depends on
    # num_weights)
    awb = op.aux_writeback(params) if callable(op.aux_writeback) \
        else op.aux_writeback
    if awb and isinstance(outs, (list, tuple)):
        visible = []
        for i, o in enumerate(outs):
            tgt_idx = awb.get(i)
            if tgt_idx is not None:
                tgt = inputs[tgt_idx]
                if isinstance(tgt, NDArray):
                    tgt._set_jax(o._jax.astype(tgt.dtype))
            else:
                visible.append(o)
        outs = visible[0] if len(visible) == 1 else visible
    # in-place ops write result back through the mutated input's chunk
    if op.mutates_input is not None:
        target = inputs[op.mutates_input]
        res = outs[0] if isinstance(outs, (list, tuple)) else outs
        target._set_jax(res._jax)
        return target
    if out is not None:
        src = outs[0] if isinstance(outs, (list, tuple)) else outs
        out._set_jax(src._jax.astype(out.dtype))
        return out
    return outs


_STORAGE_FALLBACK_WARNED = set()


def _warn_storage_fallback(op_name, stype):
    if (op_name, stype) not in _STORAGE_FALLBACK_WARNED:
        _STORAGE_FALLBACK_WARNED.add((op_name, stype))
        import warnings
        warnings.warn(
            "op %s has no sparse implementation for stype=%r; converting "
            "to dense (reference: MXNet storage-fallback warning)"
            % (op_name, stype))


def _wrap_one(o, ctx):
    # ops may return already-wrapped NDArrays / sparse arrays (no_jit
    # graph ops); pass them through instead of re-wrapping
    if isinstance(o, NDArray) or hasattr(o, "stype"):
        return o
    return NDArray(o, ctx=ctx)


def _wrap_outputs(op, outs, ctx):
    if isinstance(outs, tuple) and op.num_outputs != 1:
        wrapped = [_wrap_one(o, ctx) for o in outs]
        engine.maybe_sync(wrapped[0]._jax
                          if isinstance(wrapped[0], NDArray) else None)
        return wrapped
    if isinstance(outs, (tuple, list)):
        outs = outs[0] if len(outs) == 1 and op.num_outputs == 1 else outs
    if isinstance(outs, (tuple, list)):
        return [_wrap_one(o, ctx) for o in outs]
    o = _wrap_one(outs, ctx)
    if isinstance(o, NDArray):
        engine.maybe_sync(o._jax)
    return o


def _unpickle_ndarray(value: _np.ndarray, dtype: str) -> NDArray:
    dt = jnp.bfloat16 if dtype == "bfloat16" else _np.dtype(dtype)
    return NDArray(jnp.asarray(value, dtype=dt))


def from_jax(value, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(value, ctx=ctx or current_context())


# ---------------------------------------------------------------------------
# creation functions (reference: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------

def _creation_dtype(dtype):
    if dtype is None:
        return _default_dtype()
    if dtype in ("bfloat16", jnp.bfloat16):
        return jnp.bfloat16
    return _np.dtype(dtype)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    ctx = ctx or current_context()
    if isinstance(source, NDArray):
        src = source._jax
        if dtype is not None:
            src = src.astype(_creation_dtype(dtype))
        return NDArray(_put(src, ctx), ctx=ctx)
    is_np = isinstance(source, _np.ndarray) or hasattr(source, "__array__")
    arr = _np.asarray(source)
    if dtype is None:
        if not is_np:
            dtype = _default_dtype()   # python lists → float32 (reference)
        elif arr.dtype == _np.float64:
            dtype = _default_dtype()   # no x64 on TPU path: narrow to f32
    if dtype is not None:
        arr = arr.astype(_creation_dtype(dtype))
    return NDArray(_put(arr, ctx), ctx=ctx)


def zeros(shape, ctx=None, dtype=None, **kw) -> NDArray:
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, numbers.Integral) else tuple(shape)
    return NDArray(_put(jnp.zeros(shape, _creation_dtype(dtype)), ctx), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kw) -> NDArray:
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, numbers.Integral) else tuple(shape)
    return NDArray(_put(jnp.ones(shape, _creation_dtype(dtype)), ctx), ctx=ctx)


def full(shape, val, ctx=None, dtype=None, **kw) -> NDArray:
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, numbers.Integral) else tuple(shape)
    return NDArray(_put(jnp.full(shape, val, _creation_dtype(dtype)), ctx), ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    ctx = ctx or current_context()
    vals = jnp.arange(start, stop, step, _creation_dtype(dtype))
    if repeat != 1:
        vals = jnp.repeat(vals, repeat)
    return NDArray(_put(vals, ctx), ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    """Reference: mx.nd.eye (M=0 means square)."""
    ctx = ctx or current_context()
    vals = jnp.eye(int(N), int(M) or None, int(k), _creation_dtype(dtype))
    return NDArray(_put(vals, ctx), ctx=ctx)


def zeros_like(a: NDArray, **kw) -> NDArray:
    return zeros(a.shape, ctx=a.context, dtype=a.dtype)


def ones_like(a: NDArray, **kw) -> NDArray:
    return ones(a.shape, ctx=a.context, dtype=a.dtype)


def concatenate(arrays: Sequence[NDArray], axis=0) -> NDArray:
    return invoke("concat", *arrays, dim=axis)


def stack_arrays(arrays: Sequence[NDArray], axis=0) -> NDArray:
    return invoke("stack", *arrays, axis=axis)


def waitall() -> None:
    engine.wait_for_all()


# ---------------------------------------------------------------------------
# serialization (reference: src/ndarray/ndarray.cc NDArray::Save/Load and
# src/c_api/c_api.cc MXNDArraySave file-dict format)
#
# Byte layout kept compatible with the reference's dense V2 format:
#   file:   uint64 list_magic=0x112, uint64 reserved,
#           uint64 ndarray_count, [each NDArray],
#           uint64 name_count, [uint64 len + utf8 bytes]
#   array:  uint32 NDARRAY_V2_MAGIC=0xF993FAC9, int32 stype(=0 dense? see
#           note: v2 writes stype only for sparse-capable builds; we always
#           write it, and accept both layouts on load),
#           uint32 ndim + uint32 dims..., int32 devtype + int32 devid,
#           int32 type_flag, raw data bytes
# ---------------------------------------------------------------------------

_LIST_MAGIC = 0x112
_NDARRAY_V1_MAGIC = 0xF993FAC8
_NDARRAY_V2_MAGIC = 0xF993FAC9
_NDARRAY_V3_MAGIC = 0xF993FACA


def _write_dense_payload(buf: bytearray, a: _np.ndarray) -> None:
    buf += struct.pack("<I", a.ndim)
    for d in a.shape:
        buf += struct.pack("<I", d)
    buf += struct.pack("<ii", 1, 0)                   # saved ctx: cpu(0)
    flag = DTYPE_TO_FLAG.get(_np.dtype(a.dtype))
    if flag is None:
        a = a.astype(_np.float32)
        flag = 0
    buf += struct.pack("<i", flag)
    if flag == 12:   # bfloat16: numpy can't memmap it; store via uint16 view
        a16 = _np.asarray(jnp.asarray(a, jnp.bfloat16)).view(_np.uint16)
        buf += a16.tobytes()
    else:
        buf += _np.ascontiguousarray(a).tobytes()


def _save_one(buf: bytearray, arr) -> None:
    # sparse stypes round-trip (reference NDArray::Save handles
    # kRowSparseStorage=1 / kCSRStorage=2 with their aux arrays; byte
    # layout here: stype, logical shape, n_aux, aux payloads..., data —
    # self-consistent, unverifiable against reference bytes offline)
    stype = getattr(arr, "stype", "default")
    if stype == "row_sparse":
        buf += struct.pack("<I", _NDARRAY_V2_MAGIC)
        buf += struct.pack("<i", 1)
        buf += struct.pack("<I", len(arr.shape))
        for d in arr.shape:
            buf += struct.pack("<I", d)
        buf += struct.pack("<I", 1)                   # n aux
        _write_dense_payload(buf, arr.indices.asnumpy().astype(_np.int64))
        _write_dense_payload(buf, arr.data.asnumpy())
        return
    if stype == "csr":
        buf += struct.pack("<I", _NDARRAY_V2_MAGIC)
        buf += struct.pack("<i", 2)
        buf += struct.pack("<I", len(arr.shape))
        for d in arr.shape:
            buf += struct.pack("<I", d)
        buf += struct.pack("<I", 2)                   # n aux
        _write_dense_payload(buf, arr.indptr.asnumpy().astype(_np.int64))
        _write_dense_payload(buf, arr.indices.asnumpy().astype(_np.int64))
        _write_dense_payload(buf, arr.data.asnumpy())
        return
    a = arr.asnumpy()
    buf += struct.pack("<I", _NDARRAY_V2_MAGIC)
    buf += struct.pack("<i", 0)                       # kDefaultStorage
    _write_dense_payload(buf, a)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, fmt: str):
        vals = struct.unpack_from("<" + fmt, self.data, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def raw(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b


def _read_dense_payload(r: "_Reader"):
    ndim = r.take("I")
    shape = tuple(int(r.take("I")) for _ in range(ndim))
    r.take("ii")                                      # saved ctx
    flag = r.take("i")
    dtype = FLAG_TO_DTYPE[flag]
    count = 1
    for d in shape:
        count *= d
    if flag == 12:
        raw = r.raw(count * 2)
        a = _np.frombuffer(raw, dtype=_np.uint16).reshape(shape)
        return jnp.asarray(a).view(jnp.bfloat16), True
    a = _np.frombuffer(r.raw(count * dtype.itemsize),
                       dtype=dtype).reshape(shape)
    return a, False


def _load_one(r: _Reader):
    magic = r.take("I")
    stype = 0
    if magic == _NDARRAY_V1_MAGIC:
        ndim = r.take("I")
        shape = tuple(int(r.take("I")) for _ in range(ndim))
        r.take("ii")
        flag = r.take("i")
        dtype = FLAG_TO_DTYPE[flag]
        count = 1
        for d in shape:
            count *= d
        a = _np.frombuffer(r.raw(count * dtype.itemsize),
                           dtype=dtype).reshape(shape)
        return array(a, dtype=a.dtype)
    if magic not in (_NDARRAY_V2_MAGIC, _NDARRAY_V3_MAGIC):
        raise MXNetError("invalid NDArray magic 0x%x" % magic)
    stype = r.take("i")
    if stype == 0:
        val, is_bf16 = _read_dense_payload(r)
        if is_bf16:
            return NDArray(val, ctx=current_context())
        return array(val, dtype=val.dtype)
    # sparse: logical shape, n_aux, aux payloads..., data
    from . import sparse as _sp
    ndim = r.take("I")
    shape = tuple(int(r.take("I")) for _ in range(ndim))
    n_aux = r.take("I")
    aux = [_read_dense_payload(r)[0] for _ in range(n_aux)]
    data, _ = _read_dense_payload(r)
    if stype == 1:                                    # row_sparse
        return _sp.RowSparseNDArray(array(data),
                                    array(_np.asarray(aux[0])), shape)
    if stype == 2:                                    # csr
        return _sp.CSRNDArray(array(data), array(_np.asarray(aux[1])),
                              array(_np.asarray(aux[0])), shape)
    raise MXNetError("unknown storage type %d in file" % stype)


def save_bytes(data) -> bytes:
    """Serialize list/dict of NDArrays to the reference's file format."""
    if isinstance(data, NDArray):
        data = [data]
    names: List[str] = []
    arrays: List[NDArray] = []
    if isinstance(data, dict):
        for k, v in data.items():
            names.append(k)
            arrays.append(v)
    else:
        arrays = list(data)
    buf = bytearray()
    buf += struct.pack("<QQ", _LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(arrays))
    for a in arrays:
        _save_one(buf, a)
    buf += struct.pack("<Q", len(names))
    for n in names:
        nb = n.encode("utf-8")
        buf += struct.pack("<Q", len(nb)) + nb
    return bytes(buf)


def load_bytes(raw: bytes):
    r = _Reader(raw)
    magic, _res = r.take("QQ")
    if magic != _LIST_MAGIC:
        raise MXNetError("invalid NDArray file magic")
    n = r.take("Q")
    arrays = [_load_one(r) for _ in range(n)]
    n_names = r.take("Q")
    if n_names == 0:
        return arrays
    names = []
    for _ in range(n_names):
        ln = r.take("Q")
        names.append(r.raw(ln).decode("utf-8"))
    return dict(zip(names, arrays))


def save(fname: str, data) -> None:
    with open(fname, "wb") as f:
        f.write(save_bytes(data))


def load(fname: str):
    with open(fname, "rb") as f:
        return load_bytes(f.read())
