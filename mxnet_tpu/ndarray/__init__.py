"""`mx.nd` namespace: NDArray + one generated function per registered op.

Reference: python/mxnet/ndarray/register.py (_make_ndarray_function) builds
these wrappers at import from the C registry; we do the same from the Python
registry (SURVEY.md §3.1).
"""
from __future__ import annotations

import sys
from types import ModuleType

from ..ops import registry as _registry
from .ndarray import (NDArray, invoke, array, zeros, ones, full, empty, arange, eye,
                      zeros_like, ones_like, concatenate, save, load,
                      save_bytes, load_bytes, waitall, from_jax)
from .ndarray import stack_arrays as _stack_arrays

__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "eye", "zeros_like", "ones_like", "concatenate",
           "save", "load", "waitall"]


def _make_op_func(opname: str):
    op = _registry.get_op(opname)

    def fn(*args, out=None, **kwargs):
        return invoke(opname, *args, out=out, **kwargs)

    fn.__name__ = opname
    fn.__doc__ = op.doc
    return fn


_this = sys.modules[__name__]
for _name in _registry.list_ops():
    if not hasattr(_this, _name) and _name.isidentifier():
        setattr(_this, _name, _make_op_func(_name))

from . import sparse
from .sparse import cast_storage, RowSparseNDArray, CSRNDArray

def Custom(*inputs, op_type=None, **kwargs):
    """User-defined op (reference: nd.Custom over src/operator/custom)."""
    from ..operator import Custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)


def stack(*data, axis=0, **kw):
    """MXNet varargs form: nd.stack(a, b, axis=0); also accepts a list."""
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _stack_arrays(data, axis=axis)


def concat(*data, dim=1, axis=None, **kw):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return invoke("concat", *data, dim=dim if axis is None else axis)


Concat = concat


# `mx.nd.random` submodule (reference: python/mxnet/ndarray/random.py)
random = ModuleType(__name__ + ".random")
random.uniform = _make_op_func("_random_uniform")
random.normal = _make_op_func("_random_normal")
random.randn = lambda *shape, **kw: random.normal(shape=shape, **kw)
random.gamma = _make_op_func("_random_gamma")
random.exponential = _make_op_func("_random_exponential")
random.poisson = _make_op_func("_random_poisson")
random.randint = _make_op_func("_random_randint")
random.bernoulli = _make_op_func("_random_bernoulli")
random.multinomial = _make_op_func("_sample_multinomial")
random.shuffle = _make_op_func("shuffle")
sys.modules[random.__name__] = random

def __getattr__(name):
    if name == "contrib":
        # reference parity: mx.nd.contrib IS the contrib op namespace
        # (same module as mx.contrib.nd); register it like .random above
        # so `import mxnet_tpu.ndarray.contrib` also works
        import sys
        from ..contrib import ndarray as contrib
        sys.modules[__name__ + ".contrib"] = contrib
        return contrib
    raise AttributeError(name)
