"""Persistent compiled-program cache (ISSUE 13 tentpole).

Every process used to pay full XLA compile cost on every cold start —
the program census measured 24s of compile wall-time for the eager
bench lane, re-paid by every supervisor respawn, chaos restart and
serve replica spawn.  The Julia→TPU AOT work (arxiv 1810.09868) treats
compiled XLA executables as serializable artifacts and TF-Serving
(arxiv 1605.08695) makes warm-up-before-traffic a first-class servable
lifecycle phase; this module is both: an on-disk store of serialized
XLA executables that a warm restart *deserializes* (~20ms) instead of
re-tracing and re-compiling (seconds), keyed so that nothing stale can
ever load.

**Key envelope.**  One cache entry is addressed by
``sha256(name | trace signature | function fingerprint | jit spec |
environment envelope)`` where

* *trace signature* is the program registry's cache key verbatim
  (tree structure + per-leaf shape/dtype/weak-type/sharding — see
  :func:`mxnet_tpu.programs.signature_of`), canonicalized to text;
* *function fingerprint* hashes the traced callable's code objects
  recursively (bytecode + nested consts + names), so an edited program
  body can never collide with its previous self;
* *jit spec* covers ``donate_argnums``/``static_argnums``/shardings —
  two sites jitting one body with different donation sets are distinct
  executables;
* *environment envelope* is (jax version, jaxlib version, backend
  platform, device kinds + count, python major.minor, a content hash
  of the mxnet_tpu library source, ``MX_COMPILE_CACHE_SALT``) — any
  skew is a MISS, never a wrong load.  The envelope is additionally
  stored INSIDE each entry and re-verified on load, so a key-scheme
  bug still cannot resurrect an executable built by a different
  toolchain.

**Fallback semantics.**  Every failure path — absent entry, envelope
skew, truncated or corrupt payload, an executable the backend refuses
to deserialize, an out-tree that will not pickle (e.g. the hybridize
train path's vjp closure) — is counted (``compile_cache.misses`` /
``compile_cache.errors``) and falls back to a normal compile.  The
cache can only ever cost a read; it can never fail a program.

**Write discipline.**  Entries are written to a per-process temp file
and published with ``os.replace`` (the checkpoint.save_sharded
pattern), so concurrent writers are last-write-wins and a reader can
never observe a torn entry; a crash mid-write leaves only a ``.tmp-*``
dropping that the next :func:`store` to the same key overwrites.

Hot-path contract (mxlint-rooted): cache I/O happens only inside
``Program._compile`` — the cold path that was about to pay seconds of
XLA compile anyway.  :func:`cache_key`/:func:`signature_token` are
pure string/hash work over host metadata; nothing here may sync a
device or run on a per-dispatch path.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .base import get_env
from . import telemetry as _telemetry

__all__ = ["enabled", "cache_dir", "envelope", "cache_key",
           "signature_token", "function_fingerprint", "load", "store",
           "stats", "reset_stats", "entry_path", "SCHEMA"]

logger = logging.getLogger("mxnet_tpu.compile_cache")

# bumped when the on-disk entry layout changes; a schema mismatch is an
# ordinary miss (old entries are simply dead weight, never read wrong)
SCHEMA = 1


def enabled() -> bool:
    """MX_COMPILE_CACHE non-empty = the persistent cache is on."""
    return bool(get_env("MX_COMPILE_CACHE", "") or "")


def cache_dir() -> str:
    return str(get_env("MX_COMPILE_CACHE", "") or "")


# ---------------------------------------------------------------------------
# Key construction
# ---------------------------------------------------------------------------

_lib_fp_lock = threading.Lock()
_lib_fp: Optional[str] = None


def _library_fingerprint() -> str:
    """Content hash over the mxnet_tpu package's python source.  A
    library edit (new trace body, changed donation set, fixed kernel)
    invalidates every entry — conservative by design: deserializing a
    stale executable silently computes the OLD code's answer."""
    global _lib_fp
    with _lib_fp_lock:
        if _lib_fp is not None:
            return _lib_fp
    h = hashlib.sha256()
    root = os.path.dirname(os.path.abspath(__file__))
    # sort dirnames IN PLACE while the walk is live: os.walk's pruning
    # contract (skip __pycache__) and deterministic order both depend
    # on mutating the list before descent
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            h.update(os.path.relpath(path, root).encode())
            try:
                # ONE walk of the library source per process (memoized
                # above); runs under the first executable build — the
                # cold path that was about to pay seconds of XLA compile
                with open(path, "rb") as f:  # mxlint: disable=host-sync-in-hot-path
                    h.update(f.read())
            except OSError:
                h.update(b"?")
    fp = h.hexdigest()[:16]
    with _lib_fp_lock:
        _lib_fp = fp
    return fp


def envelope() -> Dict[str, str]:
    """The environment identity an entry is only valid under.  Stored in
    every entry and re-checked on load; any mismatch is a miss."""
    import sys
    import jax
    try:
        devs = jax.devices()
        kinds = ",".join(sorted({d.device_kind for d in devs}))
        n = len(devs)
        backend = jax.default_backend()
    except Exception:           # backend not initializable: key degrades
        kinds, n, backend = "?", 0, "?"
    jaxlib_ver = ""
    try:
        import jaxlib
        jaxlib_ver = getattr(jaxlib, "__version__", "")
    except Exception:
        pass
    return {
        "schema": str(SCHEMA),
        "jax": jax.__version__,
        "jaxlib": jaxlib_ver,
        "backend": backend,
        "device_kinds": kinds,
        "device_count": str(n),
        "python": "%d.%d" % sys.version_info[:2],
        "library": _library_fingerprint(),
        "salt": str(get_env("MX_COMPILE_CACHE_SALT", "") or ""),
    }


def _leaf_token(sig) -> str:
    """One registry leaf signature as stable text.  Aval leaves render
    shape/dtype/weak-type plus the sharding's str() (device placement &
    PartitionSpec both key the executable); everything else via repr."""
    if isinstance(sig, tuple) and sig and sig[0] == "aval":
        _, aval, sharding = sig
        return "aval:%s:%s:%s:%s" % (
            tuple(int(s) for s in aval.shape), aval.dtype,
            bool(getattr(aval, "weak_type", False)),
            "" if sharding is None else str(sharding))
    return repr(sig)


def signature_token(sig: Tuple) -> str:
    """Canonical text form of a programs.signature_of() value."""
    treedef, leaf_sigs = sig
    return "%s|%s" % (str(treedef),
                      ";".join(_leaf_token(s) for s in leaf_sigs))


_ADDR_RE = None


def _stable_repr(obj) -> str:
    """repr() with memory addresses stripped — `<function f at 0x7f..>`
    must hash identically across processes."""
    global _ADDR_RE
    if _ADDR_RE is None:
        import re
        _ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")
    return _ADDR_RE.sub("", repr(obj))


_FP_MAX_DEPTH = 8


def function_fingerprint(fn) -> str:
    """Recursive hash of a callable's code objects (bytecode, nested
    code consts, names) AND the host values it closes over (closure
    cells, argument defaults, ``functools.partial`` bindings).

    The closure walk is the load-bearing half: trace bodies like
    ``_traced_step_window`` bake closed-over host config — weight
    decays, rescale factors, metric kernels, return flags — into the
    compiled program as constants, invisibly to the trace signature.
    Two configurations with identical shapes MUST key differently or a
    warm restart would deserialize the other config's executable and
    silently train with its constants.  Nested functions recurse (a
    closed-over Gluon block contributes its architecture ``repr``);
    frozenset constants hash in sorted order and memory addresses are
    stripped (set repr order and ids are per-process-randomized).
    Opaque objects degrade to their stable repr — the residual
    collision risk (two objects whose repr AND every reachable
    shape/value coincide while their traces differ) is documented in
    ARCHITECTURE.md's invalidation rules."""
    import functools as _ft
    h = hashlib.sha256()
    seen = set()

    def const_token(c) -> str:
        if hasattr(c, "co_code"):
            walk(c)
            return "<code>"
        if isinstance(c, (frozenset, set)):
            return "fs{%s}" % ",".join(sorted(const_token(x) for x in c))
        if isinstance(c, tuple):
            return "(%s)" % ",".join(const_token(x) for x in c)
        return _stable_repr(c)

    def walk(code):
        if id(code) in seen:
            return
        seen.add(id(code))
        h.update(code.co_code)
        h.update(",".join(code.co_names).encode())
        h.update(",".join(code.co_varnames).encode())
        for const in code.co_consts:
            h.update(const_token(const).encode())

    def feed_value(v, depth):
        if depth > _FP_MAX_DEPTH:
            h.update(b"<depth>")
            return
        if callable(v) and (hasattr(v, "__code__")
                            or isinstance(v, _ft.partial)):
            feed(v, depth)
        elif isinstance(v, (list, tuple)):
            h.update(b"seq%d" % len(v))
            for x in v:
                feed_value(x, depth + 1)
        elif isinstance(v, dict):
            for k in sorted(v, key=repr):
                h.update(_stable_repr(k).encode())
                feed_value(v[k], depth + 1)
        else:
            try:
                h.update(_stable_repr(v)[:2000].encode())
            except Exception:
                h.update(type(v).__name__.encode())

    def feed(obj, depth=0):
        if id(obj) in seen or depth > _FP_MAX_DEPTH:
            return
        seen.add(id(obj))
        if isinstance(obj, _ft.partial):
            feed(obj.func, depth + 1)
            feed_value(tuple(obj.args), depth + 1)
            for k in sorted(obj.keywords or {}):
                h.update(k.encode())
                feed_value(obj.keywords[k], depth + 1)
            return
        obj = getattr(obj, "__wrapped__", obj)
        code = getattr(obj, "__code__", None)
        if code is None:
            h.update(_stable_repr(obj).encode())
            return
        walk(code)
        for d in (getattr(obj, "__defaults__", None) or ()):
            feed_value(d, depth + 1)
        for k in sorted(getattr(obj, "__kwdefaults__", None) or {}):
            h.update(k.encode())
            feed_value(obj.__kwdefaults__[k], depth + 1)
        cells = getattr(obj, "__closure__", None) or ()
        for name, cell in zip(code.co_freevars, cells):
            h.update(name.encode())
            try:
                feed_value(cell.cell_contents, depth + 1)
            except ValueError:        # empty cell
                h.update(b"<empty>")

    feed(fn)
    return h.hexdigest()[:16]


def cache_key(name: str, sig: Tuple, fn=None,
              jit_kw: Optional[Dict[str, Any]] = None) -> str:
    """The entry's file-name identity (sha256 hex)."""
    h = hashlib.sha256()
    h.update(name.encode())
    h.update(b"\0")
    h.update(signature_token(sig).encode())
    h.update(b"\0")
    if fn is not None:
        h.update(function_fingerprint(fn).encode())
    h.update(b"\0")
    kw = jit_kw or {}
    h.update(json.dumps({k: repr(v) for k, v in sorted(kw.items())},
                        sort_keys=True).encode())
    h.update(b"\0")
    h.update(json.dumps(envelope(), sort_keys=True).encode())
    return h.hexdigest()


def entry_path(key: str) -> str:
    return os.path.join(cache_dir(), key[:2], key + ".xcache")


# ---------------------------------------------------------------------------
# Telemetry
# ---------------------------------------------------------------------------

def _counter(name, doc, **labels):
    return _telemetry.registry.counter(name, doc=doc,
                                       labels=labels or None)


def _c_hits():
    return _counter("compile_cache.hits",
                    "programs warm-started from the persistent "
                    "compiled-program cache (deserialize, no compile)")


def _c_misses(reason: str):
    return _counter("compile_cache.misses",
                    "persistent-cache lookups that fell back to a "
                    "normal compile, by reason",
                    reason=reason)


def _c_errors():
    return _counter("compile_cache.errors",
                    "persistent-cache read/write failures (corrupt "
                    "entry, unserializable executable, I/O error) — "
                    "all non-fatal, all fell back to compile")


def _c_writes():
    return _counter("compile_cache.writes",
                    "executables serialized into the persistent cache")


def _c_bytes(direction: str):
    return _counter("compile_cache.bytes",
                    "persistent-cache payload bytes moved",
                    direction=direction)


def _h_deser():
    return _telemetry.registry.histogram(
        "compile_cache_deserialize_seconds",
        doc="wall-clock time to load+deserialize one cached executable")


def stats() -> Dict[str, Any]:
    """Roll-up for bench reports / the serve spawn banner."""
    reg = _telemetry.registry
    reasons = {}
    for inst in reg.instruments():
        if inst.name == "compile_cache.misses":
            reasons[inst.labels.get("reason", "?")] = inst.value
    return {
        "enabled": enabled(),
        "dir": cache_dir() or None,
        "hits": reg.value("compile_cache.hits"),
        "misses": sum(reasons.values()),
        "miss_reasons": reasons,
        "errors": reg.value("compile_cache.errors"),
        "writes": reg.value("compile_cache.writes"),
        "xla_cache_hits": reg.value("compile_cache.xla_hits"),
        "xla_cache_misses": reg.value("compile_cache.xla_misses"),
    }


def reset_stats() -> None:
    """Zero the cache counters (tests; registry instruments persist)."""
    reg = _telemetry.registry
    for inst in list(reg.instruments()):
        if inst.name.startswith("compile_cache") and \
                isinstance(inst, _telemetry.Counter):
            inst.set(0)


# ---------------------------------------------------------------------------
# The XLA-level second layer: jax's persistent compilation cache
# ---------------------------------------------------------------------------
#
# The executable store above needs an arrays-only in/out tree (pickled
# alongside the payload).  The hybridize TRAIN lane ships a vjp closure
# across its jit boundary — per-process function objects that can
# neither pickle nor key stably — so those programs can never use the
# store.  jax's own persistent compilation cache (keyed on the
# optimized-HLO hash, so it needs no tree serialization) covers exactly
# that residue: a warm process still pays TRACING for those sites but
# skips XLA optimization+codegen.  activate() arms it under
# <MX_COMPILE_CACHE>/xla and maps jax's cache-hit/miss monitoring
# events onto compile_cache.xla_hits / xla_misses.

_activate_lock = threading.Lock()
_activated = False


def _on_jax_event(name: str, **kw) -> None:
    if name == "/jax/compilation_cache/cache_hits":
        _counter("compile_cache.xla_hits",
                 "XLA-level persistent-cache hits (jax compilation "
                 "cache under MX_COMPILE_CACHE/xla: trace paid, "
                 "XLA compile skipped)").inc()
    elif name == "/jax/compilation_cache/cache_misses":
        _counter("compile_cache.xla_misses",
                 "XLA-level persistent-cache misses (cold compile, "
                 "entry written for the next process)").inc()


def activate() -> bool:
    """Arm both cache layers for this process (idempotent).  Called by
    ``programs.register_program`` on first use, so every jit site —
    AOT or light — is covered the moment MX_COMPILE_CACHE is set."""
    global _activated
    if not enabled():
        return False
    with _activate_lock:
        if _activated:
            return True
        _activated = True
    try:
        import jax
        from jax import monitoring as _mon
        xla_dir = os.path.join(cache_dir(), "xla")
        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        # default thresholds skip sub-second/small programs — exactly
        # the long tail a warm restart re-pays 100x of
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _mon.register_event_listener(_on_jax_event)
    except Exception as e:
        logger.warning("compile_cache: XLA-layer cache unavailable "
                       "(%s: %s); executable store still active",
                       type(e).__name__, e)
        return True
    return True


# ---------------------------------------------------------------------------
# Load / store
# ---------------------------------------------------------------------------

def load(name: str, key: str):
    """Deserialize the cached executable for `key`, or None.

    Every failure mode is a counted miss (and for corrupt payloads an
    error too); this function never raises.  A hit returns a live
    ``jax.stages.Compiled`` — donation aliasing, memory_analysis and
    cost_analysis all intact."""
    if not enabled():
        return None
    path = entry_path(key)
    t0 = time.perf_counter()
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        _c_misses("absent").inc()
        return None
    try:
        entry = pickle.loads(blob)
        if not isinstance(entry, dict) or entry.get("schema") != SCHEMA:
            raise ValueError("bad schema %r" %
                             (entry.get("schema")
                              if isinstance(entry, dict) else type(entry)))
        if entry.get("envelope") != envelope():
            # belt over the key's own envelope hash: version/topology
            # skew can NEVER load (e.g. a key-construction bug)
            _c_misses("envelope").inc()
            logger.info("compile_cache: envelope skew for %r (%s); "
                        "recompiling", name, path)
            return None
        from jax.experimental import serialize_executable as _se
        compiled = _se.deserialize_and_load(*entry["payload"])
    except Exception as e:
        _c_misses("corrupt").inc()
        _c_errors().inc()
        logger.warning("compile_cache: unreadable entry for %r (%s: %s); "
                       "recompiling", name, type(e).__name__, e)
        # best-effort removal so the poisoned entry is not re-parsed on
        # every future cold start
        try:
            os.remove(path)
        except OSError:
            pass
        return None
    dt = time.perf_counter() - t0
    _c_hits().inc()
    _c_bytes("read").inc(len(blob))
    _h_deser().observe(dt)
    logger.info("compile_cache: warm-started %r in %.1fms (%d bytes)",
                name, dt * 1e3, len(blob))
    return compiled


def store(name: str, key: str, compiled) -> bool:
    """Serialize `compiled` under `key` (temp + atomic rename).  Returns
    False (counted, never raises) when the executable cannot be
    serialized or the write fails."""
    if not enabled():
        return False
    try:
        from jax.experimental import serialize_executable as _se
        payload = _se.serialize(compiled)
        blob = pickle.dumps({
            "schema": SCHEMA,
            "name": name,
            "envelope": envelope(),
            "created": time.time(),
            "payload": payload,
        }, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:
        _c_errors().inc()
        logger.info("compile_cache: %r is not serializable (%s: %s); "
                    "this program stays compile-on-start",
                    name, type(e).__name__, e)
        return False
    path = entry_path(key)
    tmp = "%s.tmp-%d-%d" % (path, os.getpid(), threading.get_ident())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)   # last-write-wins; readers never see torn
    except OSError as e:
        _c_errors().inc()
        logger.warning("compile_cache: write failed for %r (%s); "
                       "continuing uncached", name, e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False
    _c_writes().inc()
    _c_bytes("written").inc(len(blob))
    return True
