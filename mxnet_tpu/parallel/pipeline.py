"""Pipeline parallelism: GPipe-style microbatch schedule over a 'pp' axis.

No reference counterpart (SURVEY.md §2.3: model parallelism in the
reference is manual group2ctx placement) — this is the TPU-native design
slot filled first-class: each device on the ``pp`` mesh axis owns ONE
stage's parameters; activations flow stage-to-stage over ICI via
``ppermute`` while microbatches fill and drain the pipe (fill-drain /
GPipe schedule: T = n_micro + n_stages - 1 ticks, bubble fraction
(n_stages-1)/T shrinks as microbatches grow).

Constraints (standard for this schedule): every stage maps activations of
one fixed shape to the same shape (transformer-block shaped), and the
stage function is shared code with per-stage parameters (the leading
parameter axis is sharded over ``pp``).  The whole schedule is one
``lax.fori_loop`` inside ``shard_map`` — differentiable end to end, so a
training step wraps it in ``jax.value_and_grad`` unchanged.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:                    # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["pipeline_apply", "pipeline_parallel"]


def pipeline_apply(stage_params, xs, *, stage_fn: Callable,
                   axis_name: str = "pp"):
    """Run the fill-drain schedule.  Call INSIDE shard_map.

    stage_params: this device's stage parameters (leading stage axis
        already split away by shard_map: each device sees its own slice).
    xs: (n_micro, micro_batch, ...) microbatched input, replicated.
    stage_fn(params, x) -> y with y.shape == x.shape.

    Returns (n_micro, micro_batch, ...) outputs — valid on the LAST stage
    (other stages hold zeros; combine with a psum/gather or read on the
    last stage only, as the loss usually lives there).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n - 1
    perm = [(j, (j + 1) % n) for j in range(n)]

    def tick(t, carry):
        state, outputs = carry
        # stage 0 injects microbatch t (clamped; beyond n_micro it keeps
        # injecting the last one — its results never reach outputs)
        inject = xs[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(idx == 0, inject, state)
        y = stage_fn(stage_params, x_in)
        # the LAST stage finishes microbatch t-(n-1) at tick t
        out_t = t - (n - 1)
        slot = jnp.clip(out_t, 0, n_micro - 1)
        write = jnp.logical_and(idx == n - 1, out_t >= 0)
        outputs = outputs.at[slot].set(
            jnp.where(write, y, outputs[slot]))
        # hand activations to the next stage (the wrap-around n-1 -> 0
        # link carries garbage that stage 0 overwrites with its inject)
        state = lax.ppermute(y, axis_name, perm)
        return state, outputs

    state0 = jnp.zeros(xs.shape[1:], xs.dtype)
    out0 = jnp.zeros_like(xs)
    if hasattr(lax, "pcast"):
        state0 = lax.pcast(state0, (axis_name,), to="varying")
        out0 = lax.pcast(out0, (axis_name,), to="varying")
    _, outputs = lax.fori_loop(0, ticks, tick, (state0, out0))
    return outputs


def pipeline_parallel(stage_fn: Callable, mesh: Mesh, *,
                      pp_axis: str = "pp", n_microbatches: int = None):
    """User-facing wrapper (reference role: the group2ctx placement UX).

    stage_fn(params, x) -> y; returns apply(stacked_params, x) where
    stacked_params has a leading stage axis of size mesh.shape[pp_axis]
    and x is (batch, ...).  The batch splits into microbatches, runs the
    schedule, and returns (batch, ...) outputs gathered from the last
    stage.
    """
    n_stages = mesh.shape[pp_axis]
    n_micro = n_microbatches or n_stages

    def inner(stacked_params, xs):
        params = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
        out = pipeline_apply(params, xs, stage_fn=stage_fn,
                             axis_name=pp_axis)
        # only the last stage holds real outputs: broadcast them to all
        # stages so the result is replicated over pp
        return lax.psum(jnp.where(lax.axis_index(pp_axis) ==
                                  lax.psum(1, pp_axis) - 1, out,
                                  jnp.zeros_like(out)), pp_axis)

    def apply(stacked_params, x):
        n_given = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if n_given != n_stages:
            raise ValueError(
                "pipeline_parallel: %d stacked stages but the %r mesh axis "
                "has %d devices (one stage per device)"
                % (n_given, pp_axis, n_stages))
        batch = x.shape[0]
        if batch % n_micro != 0:
            raise ValueError("batch (%d) must divide into %d microbatches"
                             % (batch, n_micro))
        xs = x.reshape((n_micro, batch // n_micro) + x.shape[1:])
        specs_in = (jax.tree_util.tree_map(lambda _: P(pp_axis),
                                           stacked_params),
                    P())
        mapped = shard_map(inner, mesh=mesh, in_specs=specs_in,
                           out_specs=P())
        out = mapped(stacked_params, xs)
        return out.reshape((batch,) + out.shape[2:])

    return apply
