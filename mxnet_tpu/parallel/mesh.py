"""Mesh construction + sharded training steps.

Reference: the data-parallel machinery of src/kvstore/ (CommDevice reduce,
KVStoreNCCL allreduce, kvstore_dist PS) and gluon Trainer's step — here ONE
jitted function over a `jax.sharding.Mesh`: the forward, loss, backward,
gradient allreduce and optimizer update compile into a single XLA program
whose collectives XLA schedules to overlap with the backward pass (the
per-key engine-op overlap property of SURVEY.md §3.5, now in the compiler).

Tensor parallelism (absent in the reference, SURVEY.md §2.3 design slot):
Megatron-style column/row sharding of Dense weights via NamedSharding —
XLA inserts the psum at the row-sharded matmul.

Multi-host: `init_process_group` wraps jax.distributed.initialize (the
`tools/launch.py` / DMLC_ROLE env role).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..gluon.block import functionalize

__all__ = ["make_mesh", "replicated", "batch_sharded", "shard_params_tp",
           "TrainStep", "init_process_group"]


def init_process_group(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None,
                       initialization_timeout: Optional[int] = None):
    """Multi-host process group over DCN (reference role: ps-lite
    Postoffice::Start + DMLC_* env; here jax.distributed.initialize).

    Arguments default from the env contract tools/launch.py sets
    (MX_COORDINATOR / MX_NUM_PROCESSES / MX_PROCESS_ID), the way the
    reference workers read DMLC_PS_ROOT_URI & co from their tracker.
    ``initialization_timeout`` (seconds, also env MX_INIT_TIMEOUT) bounds
    the coordinator handshake so a failed pairing surfaces as an error the
    launcher can retry with a fresh port instead of a 5-minute hang.
    """
    from ..base import get_env
    if coordinator_address is None:
        coordinator_address = get_env("MX_COORDINATOR") or None
    if num_processes is None and get_env("MX_NUM_PROCESSES"):
        num_processes = int(get_env("MX_NUM_PROCESSES"))
    if process_id is None and get_env("MX_PROCESS_ID"):
        process_id = int(get_env("MX_PROCESS_ID"))
    if initialization_timeout is None and get_env("MX_INIT_TIMEOUT"):
        initialization_timeout = int(get_env("MX_INIT_TIMEOUT"))
    kwargs = {}
    if initialization_timeout is not None:
        import inspect
        import warnings
        sig = inspect.signature(jax.distributed.initialize)
        if "initialization_timeout" in sig.parameters:
            kwargs["initialization_timeout"] = initialization_timeout
        else:
            warnings.warn("this jax has no initialization_timeout kwarg; "
                          "the requested %ss handshake bound is ignored"
                          % initialization_timeout)
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kwargs)


def make_mesh(axes: Sequence[str] = ("dp",),
              shape: Optional[Sequence[int]] = None,
              devices=None) -> Mesh:
    """Build a Mesh over the visible devices.

    Default: all devices on one 'dp' axis.  shape=(dp, tp) splits them 2-D;
    -1 infers one dimension.  On a real pod, jax's device order keeps ICI
    neighbours adjacent, so the innermost axis gets the fastest links.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = [n] + [1] * (len(axes) - 1)
    shape = list(shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = n // known
    arr = _np.asarray(devices[:int(_np.prod(shape))]).reshape(shape)
    return Mesh(arr, tuple(axes))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard axis 0 (batch) over the data-parallel mesh axis."""
    return NamedSharding(mesh, P(axis))


def shard_params_tp(param_values: Dict[str, jax.Array], mesh: Mesh,
                    tp_axis: str = "tp",
                    rules: Optional[Dict[str, Any]] = None):
    """Deprecated thin alias: Megatron-style TP placement for Dense
    weights, now owned by :mod:`mxnet_tpu.parallel.speclayout` (the one
    source of truth for parameter shardings — ISSUE 14).  Same
    semantics as ever: explicit ``rules`` ({name-substring:
    PartitionSpec}; unmatched params replicate), else column/row
    alternation for consecutive 2-D '.weight' params.  New code should
    build a :class:`~mxnet_tpu.parallel.speclayout.SpecLayout` and call
    :func:`~mxnet_tpu.parallel.speclayout.shard_params` (which adds the
    fsdp/ZeRO sheet-sharding this TP-only surface never had).

    NOTE: sharding choices here NEVER change results — XLA inserts the
    collectives that preserve the math; a suboptimal layout only costs
    communication.
    """
    from .speclayout import shard_params_tp as _impl
    return _impl(param_values, mesh, tp_axis=tp_axis, rules=rules)


class TrainStep:
    """One jitted data-parallel (+optional TP) training step.

    Built from a Gluon block via functionalize(); the returned callable has
    signature step(params, opt_state, *batch) -> (params, opt_state, loss).
    SGD+momentum by default (enough for the dry-run and the bench; the full
    optimizer set runs through gluon.Trainer's eager path).
    """

    def __init__(self, block, loss_fn: Callable, mesh: Mesh,
                 learning_rate: float = 0.01, momentum: float = 0.9,
                 dp_axis: str = "dp", tp_axis: str = "tp",
                 tp_rules: Optional[Dict[str, Any]] = None,
                 donate: bool = True):
        pure_fn, param_values = functionalize(block)
        self.mesh = mesh
        self.params = shard_params_tp(param_values, mesh, tp_axis, tp_rules)
        self.opt_state = jax.tree_util.tree_map(jnp.zeros_like, self.params)
        self._batch_sharding = batch_sharded(mesh, dp_axis)
        lr, mom = learning_rate, momentum

        def step(params, opt_state, *batch):
            def loss_of(p):
                out = pure_fn(p, *batch[:-1], training=True)
                return loss_fn(out, batch[-1])

            loss, grads = jax.value_and_grad(loss_of)(params)
            # batch is dp-sharded: jax.grad's sum over examples makes XLA
            # emit the gradient all-reduce (psum over 'dp') automatically,
            # overlapped with backward by the latency-hiding scheduler
            new_opt = jax.tree_util.tree_map(
                lambda m, g: mom * m - lr * g, opt_state, grads)
            new_params = jax.tree_util.tree_map(
                lambda p, m: p + m, params, new_opt)
            return new_params, new_opt, loss

        self._step_fn = step
        self._donate = donate
        from ..programs import register_program
        self._step = register_program(
            "mesh.train_step", step, mode="light",
            donate_argnums=(0, 1) if donate else ())
        self._multi = {}

    def shard_batch(self, *arrays):
        """Place host batches onto the dp-sharded layout.  Multi-host: each
        process passes its LOCAL shard (the data-loader's part_index slice)
        and the pieces assemble into one global array — the reference's
        dist-training contract where every worker feeds its own partition."""
        if jax.process_count() > 1:
            return tuple(
                jax.make_array_from_process_local_data(
                    self._batch_sharding, _np.asarray(a))
                for a in arrays)
        return tuple(jax.device_put(a, self._batch_sharding) for a in arrays)

    def run_steps(self, k: int, *batch):
        """Run k steps under ONE jit dispatch (lax.fori_loop over the step
        body, same batch each iteration).  Perf diagnostic: comparing
        k-step against k x one-step isolates per-step dispatch/transfer
        overhead (tunnel RPC, host work) from device compute — the
        reference's benchmark_score.py plays the same trick with its
        wait_to_read-once loop."""
        batch = self.shard_batch(*batch)
        if k not in self._multi:
            step_fn = self._step_fn

            def multi(params, opt_state, *b):
                def body(_, carry):
                    p, o, _loss = carry
                    p, o, loss = step_fn(p, o, *b)
                    return p, o, loss.astype(jnp.float32)
                return lax.fori_loop(
                    0, k, body,
                    (params, opt_state, jnp.zeros((), jnp.float32)))
            from ..programs import register_program
            self._multi[k] = register_program(
                "mesh.train_window", multi, mode="light",
                donate_argnums=(0, 1) if self._donate else ())
        self.params, self.opt_state, loss = self._multi[k](
            self.params, self.opt_state, *batch)
        return loss

    def __call__(self, *batch):
        batch = self.shard_batch(*batch)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, *batch)
        return loss

    def save(self, path: str) -> None:
        """Sharded checkpoint of params+opt_state (mxnet_tpu.checkpoint)."""
        from ..checkpoint import save_sharded
        save_sharded(path, {"params": self.params,
                            "opt_state": self.opt_state})

    def restore(self, path: str) -> None:
        """Restore in place, re-laying-out onto THIS step's shardings
        (elastic: the saving mesh may have differed)."""
        from ..checkpoint import restore_sharded
        state = restore_sharded(
            path,
            template={"params": self.params, "opt_state": self.opt_state})
        self.params = state["params"]
        self.opt_state = state["opt_state"]

    def write_back(self, block):
        """Copy trained params back into the Block's Parameters.

        Step params are GLOBAL (mesh-sharded/replicated) arrays; the
        block's NDArrays are single-device — materialize the local copy
        (replicated: shard 0 IS the value; sharded: gather first) and
        re-home it, or later eager ops mix single- and multi-device
        operands and fail."""
        params = block.collect_params()
        for name, v in self.params.items():
            arr = params[name].data()
            if hasattr(v, "sharding") and not isinstance(
                    v.sharding, jax.sharding.SingleDeviceSharding):
                if getattr(v.sharding, "is_fully_replicated", False):
                    local = v.addressable_data(0)
                elif jax.process_count() > 1:
                    # spans non-addressable devices: multihost gather
                    from jax.experimental import multihost_utils
                    local = multihost_utils.process_allgather(
                        v, tiled=True)
                else:
                    local = jax.device_get(v)      # gather sharded param
            else:
                local = v
            arr._set_jax(jax.device_put(jnp.asarray(local),
                                        arr.context.jax_device)
                         .astype(arr.dtype))
