"""Sequence/context parallelism: ring attention + all-to-all (Ulysses).

No reference counterpart (SURVEY.md §5.7: the reference caps at
single-device attention) — this is the TPU-native long-context layer the
rebuild adds as first-class: sequences sharded over an 'sp' mesh axis so
context length scales with the number of chips.

Two standard schemes, both over ``shard_map``:

* **Ring attention** (`ring_attention`): K/V blocks rotate around the sp
  ring via ``ppermute`` while each device's Q stays put; partial attention
  accumulates with the online-softmax (flash) recurrence, so the full
  L×L score matrix never materializes and each hop's compute overlaps the
  next hop's ICI transfer (XLA's latency-hiding scheduler).  Memory per
  chip: O(L/n · L/n) per block instead of O(L²).
* **Ulysses / all-to-all** (`ulysses_attention`): ``all_to_all`` swaps the
  sharded axis from sequence to heads, runs exact local attention on full
  sequences for H/n heads, and swaps back.  Cheaper at moderate L (two
  all-to-alls), requires heads % n == 0.

Both are differentiable (shard_map + collectives have transfer rules), so
they drop into training steps; numerical equality against single-device
attention is pinned by tests on the 8-device CPU mesh.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                   # jax >= 0.7 canonical location
    from jax import shard_map
except ImportError:                    # older: experimental alias
    from jax.experimental.shard_map import shard_map

__all__ = ["ring_attention", "ulysses_attention",
           "context_parallel_attention"]


def _block_attn(q, k, v, q_off, k_off, causal, scale):
    """One (q-block × kv-block) partial flash step.

    Returns (o_partial, m_block, l_block): unnormalized output, row max,
    row sum for the online-softmax merge.  Shapes: q (B, Lq, H, D),
    k/v (B, Lk, H, D).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        kpos = k_off + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                       # (B, H, Lq)
    # all-masked rows: exp(-inf - -inf) = nan; pin m to 0 there
    m = jnp.where(jnp.isneginf(m), 0.0, m)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # (B, H, Lq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)       # unnormalized
    return o, m, l


def _flash_ok(q, k) -> bool:
    """Shard shapes eligible for the blockwise Pallas kernel per hop —
    same gate as attention_core: Mosaic on TPU (or the 'pallas' lowering
    config forced, which interprets off-TPU), never interpret-by-default
    on CPU/GPU where the compiled jnp path is far faster."""
    from ..ops import attention as _att
    impl = _att.current_attention_impl()   # per-block scope wins over global
    if impl == "xla":
        return False
    lq, lk, d = q.shape[1], k.shape[1], q.shape[3]
    aligned = (lq % _att._BLOCK_Q == 0 and lk % _att._BLOCK_K == 0
               and d % 128 == 0)
    return aligned and (_att._on_tpu() or impl == "pallas")


def _ring_attention_flash(q, k, v, *, axis_name, causal, scale):
    """Flash-kernel ring: each hop runs the blockwise Pallas kernel on its
    K/V shard, producing a NORMALIZED partial plus its logsumexp; partials
    merge with the standard (out, lse) combine
        lse' = logaddexp(lse, lse_b);  out' = out·e^{lse-lse'} + out_b·e^{lse_b-lse'}
    so per-hop memory is O(L/n · D) and the score matrix never exists.
    q/k/v here are (B, Lq, H, D) (sequence-sharded); kernel layout is
    (B, H, L, D)."""
    from ..ops.attention import flash_attention_with_lse
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    qh = q.transpose(0, 2, 1, 3)                   # (B, H, Lq, D)

    def hop(kt, vt, src):
        kh = kt.transpose(0, 2, 1, 3)
        vh = vt.transpose(0, 2, 1, 3)

        def full(_):
            return flash_attention_with_lse(qh, kh, vh, scale, False)

        def diag(_):
            return flash_attention_with_lse(qh, kh, vh, scale, True)

        def skip(_):
            z = jnp.zeros(qh.shape, qh.dtype)
            neg = jnp.full(qh.shape[:3], -jnp.inf, jnp.float32)
            # match the pallas branches' varying-axes type (check_vma)
            if hasattr(lax, "pcast"):
                z, neg = (lax.pcast(x, (axis_name,), to="varying")
                          for x in (z, neg))
            else:
                z, neg = (lax.pvary(x, (axis_name,)) for x in (z, neg))
            return z, neg
        if not causal:
            return full(None)
        # causal over the GLOBAL sequence: earlier shards attend fully,
        # same shard causally, later shards not at all
        branch = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        return lax.switch(branch, [full, diag, skip], None)

    # the ring length is STATIC (mesh axis size): unroll in Python — each
    # hop's kernel launch can then overlap the next hop's ppermute (XLA's
    # latency-hiding scheduler), and no loop-carried pallas lowering is
    # needed
    out = jnp.zeros(qh.shape, jnp.float32)
    lse = jnp.full(qh.shape[:3], -jnp.inf, jnp.float32)
    if hasattr(lax, "pcast"):
        out, lse = (lax.pcast(x, (axis_name,), to="varying")
                    for x in (out, lse))
    else:
        out, lse = (lax.pvary(x, (axis_name,)) for x in (out, lse))
    kt, vt = k, v
    perm = [(j, (j + 1) % n) for j in range(n)]
    for t in range(n):
        src = (idx - t) % n
        out_b, lse_b = hop(kt, vt, src)
        lse_new = jnp.logaddexp(lse, lse_b)
        lse_safe = jnp.where(jnp.isfinite(lse_new), lse_new, 0.0)
        wa = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_safe), 0.0)
        wb = jnp.where(jnp.isfinite(lse_b), jnp.exp(lse_b - lse_safe), 0.0)
        out = out * wa[..., None] + out_b.astype(jnp.float32) * wb[..., None]
        lse = lse_new
        if t != n - 1:
            kt = lax.ppermute(kt, axis_name, perm)
            vt = lax.ppermute(vt, axis_name, perm)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over the ``axis_name`` collective axis.

    Call INSIDE shard_map with q/k/v sequence-sharded on that axis:
    q, k, v: (B, L_local, H, D).  Returns (B, L_local, H, D).

    Hops run the blockwise Pallas flash kernel when the shard shapes are
    block-aligned (Mosaic on TPU, interpret elsewhere); otherwise the jnp
    online-softmax block recurrence below.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    lq = q.shape[1]
    lk = k.shape[1]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if _flash_ok(q, k):
        return _ring_attention_flash(q, k, v, axis_name=axis_name,
                                     causal=causal, scale=scale)
    q_off = idx * lq

    # checkpoint the block step: backward recomputes the block's score
    # matrix instead of saving it as a scan residual — per-device backward
    # memory drops from O(n·(L/n)²) to O(L/n·D), matching the flash
    # kernel's recompute-from-stats design (ops/attention._flash_bwd)
    blk = jax.checkpoint(
        lambda q_, k_, v_, qo, ko: _block_attn(q_, k_, v_, qo, ko,
                                               causal, scale))

    def body(t, carry):
        o, m, l, kt, vt = carry
        # block t originated on device (idx - t) mod n
        src = (idx - t) % n
        ob, mb, lb = blk(q, kt, vt, q_off, src * lk)
        # online-softmax merge of (o, m, l) with the new block
        m_new = jnp.maximum(m, mb)
        alpha = jnp.exp(m - m_new)                # rescale old accumulator
        beta = jnp.exp(mb - m_new)
        l_new = l * alpha + lb * beta
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + \
            ob * beta.transpose(0, 2, 1)[..., None]
        # rotate K/V around the ring for the next step
        perm = [(j, (j + 1) % n) for j in range(n)]
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        return o_new, m_new, l_new, kt, vt

    o0 = jnp.zeros(q.shape, jnp.promote_types(q.dtype, jnp.float32))
    m0 = jnp.full((q.shape[0], q.shape[2], lq), -jnp.inf)
    l0 = jnp.zeros((q.shape[0], q.shape[2], lq))
    # the loop body makes these device-varying over sp (they depend on
    # axis_index); mark the initial carry to match (shard_map vma typing)
    if hasattr(lax, "pcast"):
        o0, m0, l0 = (lax.pcast(x, (axis_name,), to="varying")
                      for x in (o0, m0, l0))
    else:
        o0, m0, l0 = (lax.pvary(x, (axis_name,)) for x in (o0, m0, l0))
    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0,
                                               k.astype(o0.dtype),
                                               v.astype(o0.dtype)))
    l = jnp.maximum(l, 1e-38)                     # fully-masked rows
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = False, scale: Optional[float] = None):
    """DeepSpeed-Ulysses SP: all_to_all seq-shard → head-shard, exact local
    attention over the FULL sequence on H/n heads, all_to_all back.

    Call INSIDE shard_map; q/k/v (B, L_local, H, D) with H % n == 0.
    """
    n = lax.psum(1, axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(
            "ulysses_attention: heads (%d) must divide by the %r axis size "
            "(%d); use ring_attention otherwise" % (q.shape[2], axis_name, n))
    if scale is None:
        scale = q.shape[-1] ** -0.5

    def seq_to_heads(x):
        # (B, L/n, H, D) -> (B, L, H/n, D): gather seq, scatter heads
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # local exact attention through the shared dispatch: blockwise Pallas
    # flash on TPU (no L×L materialization); jnp fallback elsewhere
    from ..ops.attention import attention_core
    out = attention_core(qh.transpose(0, 2, 1, 3), kh.transpose(0, 2, 1, 3),
                         vh.transpose(0, 2, 1, 3), scale=scale,
                         causal=causal)
    return heads_to_seq(out.transpose(0, 2, 1, 3).astype(q.dtype))


def context_parallel_attention(q, k, v, mesh: Mesh, *, sp_axis: str = "sp",
                               causal: bool = False, method: str = "ring",
                               scale: Optional[float] = None):
    """User-facing wrapper: shard q/k/v (B, L, H, D) over ``sp_axis`` on
    dim 1 and run the chosen SP attention.  Output sharding matches input.
    """
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[method]
    spec = P(None, sp_axis, None, None)
    inner = functools.partial(fn, axis_name=sp_axis, causal=causal,
                              scale=scale)
    # check_vma/check_rep off: interpret-mode pallas inside shard_map trips
    # jax's varying-axes checker on kernel constants ("Primitive mul
    # requires varying manual axes to match ... as a temporary workaround
    # pass check_vma=False") — the jax-recommended workaround
    try:
        mapped = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
    except TypeError:   # older jax spells it check_rep
        mapped = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_rep=False)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return mapped(q, k, v)
