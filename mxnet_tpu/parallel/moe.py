"""Expert parallelism: gated mixture-of-experts over an 'ep' mesh axis.

No reference counterpart (SURVEY.md §2.3 design slot) — TPU-native MoE:
experts live sharded across the ``ep`` axis (``e_local`` per device);
tokens are top-1 routed, packed to a fixed per-expert capacity (static
shapes — XLA requirement), exchanged with TWO ``all_to_all`` collectives
(dispatch, return), and combined scaled by the gate probability.  Dropped
tokens (over capacity) contribute zeros, the standard GShard/Switch
behavior; gradients flow through the gate via the combine weights.

Everything is jittable and differentiable; correctness is pinned against
a per-token dense reference on the 8-device CPU mesh.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:                    # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

__all__ = ["moe_apply", "moe_parallel", "top1_dispatch"]


def top1_dispatch(gate_logits, n_experts: int, capacity: int):
    """Build dispatch/combine tensors for top-1 routing.

    gate_logits: (T, E).  Returns (dispatch (T,E,C) one-hot placement,
    combine (T,E,C) = dispatch * gate_prob, aux_loss scalar — the Switch
    load-balancing loss).
    """
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (T,)
    onehot = jax.nn.one_hot(expert, n_experts, dtype=probs.dtype)
    gate = jnp.sum(probs * onehot, axis=-1)              # (T,)
    # position of each token within its expert's queue (1-based at the
    # selected expert, 0 elsewhere; summing over E extracts it)
    pos = jnp.cumsum(onehot, axis=0) * onehot
    keep = (pos <= capacity) & (onehot > 0)
    position = pos.sum(axis=-1).astype(jnp.int32) - 1    # (T,), 0-based
    loc = jax.nn.one_hot(position, capacity, dtype=probs.dtype)  # (T, C)
    dispatch = loc[:, None, :] * keep.astype(probs.dtype)[:, :, None]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * sum_e (fraction tokens to e) * (mean prob to e)
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = n_experts * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def moe_apply(x, gate_w, expert_params, *, expert_fn: Callable,
              axis_name: str = "ep", capacity_factor: float = 2.0):
    """Call INSIDE shard_map.  x: (T_local, d) tokens on this device;
    gate_w: (d, E) replicated; expert_params: this device's experts with
    leading axis e_local.  Returns (y (T_local, d), aux_loss)."""
    n = lax.psum(1, axis_name)
    e_local = jax.tree_util.tree_leaves(expert_params)[0].shape[0]
    n_experts = n * e_local
    if gate_w.shape[-1] != n_experts:
        raise ValueError(
            "moe: gate_w routes to %d experts but %d are stacked "
            "(%d devices x %d local)" % (gate_w.shape[-1], n_experts, n,
                                         e_local))
    t_local = x.shape[0]
    capacity = max(1, int(capacity_factor * t_local / n_experts))

    logits = x @ gate_w                                  # (T, E)
    dispatch, combine, aux = top1_dispatch(logits, n_experts, capacity)
    # pack: (E, C, d) expert-major token buffers
    xin = jnp.einsum("tec,td->ecd", dispatch, x)
    # dispatch all_to_all: every device keeps its e_local experts' buffers
    # from ALL devices -> (e_local, n*C, d)
    xin = lax.all_to_all(xin, axis_name, split_axis=0, concat_axis=1,
                         tiled=True)
    yout = jax.vmap(expert_fn)(expert_params, xin)       # (e_local, n*C, d)
    # return all_to_all: back to (E, C, d) token-origin layout
    yout = lax.all_to_all(yout, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, yout)
    return y, lax.pmean(aux, axis_name)


def moe_parallel(expert_fn: Callable, mesh: Mesh, *, ep_axis: str = "ep",
                 capacity_factor: float = 2.0):
    """User-facing wrapper: apply(x, gate_w, stacked_expert_params) with
    x (tokens, d) sharded over ``ep_axis``, experts stacked on a leading
    axis of size n_devices*e_local and sharded over ``ep_axis``.
    Returns (y, aux_loss)."""

    def inner(x, gate_w, expert_params):
        return moe_apply(x, gate_w, expert_params, expert_fn=expert_fn,
                         axis_name=ep_axis,
                         capacity_factor=capacity_factor)

    def apply(x, gate_w, stacked_expert_params):
        espec = jax.tree_util.tree_map(lambda _: P(ep_axis),
                                       stacked_expert_params)
        mapped = shard_map(inner, mesh=mesh,
                           in_specs=(P(ep_axis), P(), espec),
                           out_specs=(P(ep_axis), P()))
        return mapped(x, gate_w, stacked_expert_params)

    return apply
