"""Parallelism over TPU meshes.

Reference role: the kvstore/comm layer (src/kvstore/, SURVEY.md §2.3/§5.8)
plus the parallelism strategies the reference lacks (TP/SP design slots).
TPU-native: `jax.sharding.Mesh` + NamedSharding + jit — XLA inserts the
collectives (psum/all-gather/reduce-scatter) and rides ICI within a slice,
DCN across slices.
"""
from .mesh import (make_mesh, replicated, batch_sharded, shard_params_tp,
                   TrainStep, init_process_group)
from .speclayout import (SpecLayout, shard_params, tp_alternation_specs,
                         layout_from_env, mesh_from_env, mesh_for_world)
from .ring import (ring_attention, ulysses_attention,
                   context_parallel_attention)
from .pipeline import pipeline_apply, pipeline_parallel
from .moe import moe_apply, moe_parallel, top1_dispatch

__all__ = ["make_mesh", "replicated", "batch_sharded", "shard_params_tp",
           "SpecLayout", "shard_params", "tp_alternation_specs",
           "layout_from_env", "mesh_from_env", "mesh_for_world",
           "TrainStep", "init_process_group", "ring_attention",
           "ulysses_attention", "context_parallel_attention",
           "pipeline_apply", "pipeline_parallel", "moe_apply",
           "moe_parallel", "top1_dispatch"]
