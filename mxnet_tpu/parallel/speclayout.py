"""SpecLayout: canonical PartitionSpecs over named data/fsdp/tp mesh axes.

The sharding layer ROADMAP item 1 asks for (promoting the SNIPPETS.md
SpecLayout / PartitionSpec-helper patterns into the real thing): one
object that owns the mapping from *parameter identity* to *placement*
so every consumer — the sharded :class:`~mxnet_tpu.step.CompiledStep`,
the kvstore exchange bodies, ``checkpoint.save_sharded`` and the buffer
census — derives the same layout from the same three named axes:

``data``
    pure data parallelism: batches split, parameters replicated.
``fsdp``
    ZeRO/FSDP: batches split AND parameters + optimizer state
    sheet-sharded — per-chip state bytes drop ~linearly with the axis
    size; XLA all-gathers parameters just in time for each use and
    reduce-scatters gradients back onto the shards.
``tp``
    Megatron tensor parallelism: weight matrices split within a layer
    (embeddings and linears), activations cross chips inside the layer.

Resolution order for one parameter's PartitionSpec (first hit wins):

1. explicit ``rules`` ({name-substring: PartitionSpec}, the operator's
   escape hatch — matching the old ``shard_params_tp(rules=...)``);
2. the owning Block's :meth:`~mxnet_tpu.gluon.block.Block.sharding_spec`
   hook (architecture-specific layouts declared next to the layer);
3. kind defaults: embedding weights shard the vocab axis over
   ``fsdp×tp``, linear (Dense) weights split ``(out, in)`` over
   ``(tp, fsdp)``;
4. everything else sheet-shards its largest divisible axis over
   ``fsdp``; scalars and indivisible shapes replicate.

Axes absent from the mesh (or of size 1) drop out of every spec, so the
same model code runs unchanged on ``data``-only, ``data×fsdp`` and
``data×fsdp×tp`` meshes — and sharding choices NEVER change results
(XLA inserts the collectives that preserve the math; a different layout
only moves communication).

``shard_params_tp`` — the pre-SpecLayout TP-only entry point from
``parallel/mesh.py`` — is folded in here (its column/row alternation is
:func:`tp_alternation_specs`); ``mesh.shard_params_tp`` remains as a
thin deprecated alias so existing callers keep working while this
module stays the one source of truth for parameter shardings.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as _np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["SpecLayout", "tp_alternation_specs", "shard_params",
           "shard_params_tp", "place_value", "layout_from_env",
           "mesh_from_env", "mesh_for_world", "parse_mesh_axes"]

# block-class-name -> {param attr name: kind}; the kind defaults of
# resolution step 3.  Extended here rather than monkey-patched so the
# mapping is greppable next to the resolution order it feeds.
_BLOCK_PARAM_KINDS = {
    "Dense": {"weight": "linear"},
    "Embedding": {"weight": "embedding"},
}


def _dim_divisible(dim: int, size: int) -> bool:
    return size > 1 and dim % size == 0


class SpecLayout:
    """Canonical PartitionSpecs for parameters/state/batches on `mesh`.

    ``rules`` maps parameter-name substrings to explicit PartitionSpecs
    (checked first, in insertion order).  Axis names default to
    ``data``/``fsdp``/``tp``; any subset may be present on the mesh —
    :meth:`infer` accepts the legacy ``dp`` spelling for the data axis.
    """

    __slots__ = ("mesh", "data_axis", "fsdp_axis", "tp_axis", "rules",
                 "_sig")

    def __init__(self, mesh: Mesh, data_axis: str = "data",
                 fsdp_axis: str = "fsdp", tp_axis: str = "tp",
                 rules: Optional[Dict[str, Any]] = None):
        self.mesh = mesh
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis
        self.rules = dict(rules or {})
        # immutable once built: the signature (consulted on every step
        # dispatch) is computed once, not O(n_devices) per step
        self._sig = (tuple(mesh.axis_names),
                     tuple(int(s) for s in mesh.shape.values()),
                     tuple(d.id for d in mesh.devices.flat),
                     data_axis, fsdp_axis, tp_axis,
                     tuple((k, repr(v))
                           for k, v in sorted(self.rules.items())))

    @classmethod
    def infer(cls, mesh: Mesh, rules: Optional[Dict[str, Any]] = None
              ) -> "SpecLayout":
        """Layout over `mesh` with the data axis name detected: the
        first axis named ``data``/``dp``/``batch``, else the first axis
        that is neither ``fsdp`` nor ``tp``."""
        names = list(mesh.axis_names)
        data = next((n for n in names if n in ("data", "dp", "batch")),
                    None)
        if data is None:
            data = next((n for n in names if n not in ("fsdp", "tp")),
                        "data")
        return cls(mesh, data_axis=data, rules=rules)

    # -- axis helpers ------------------------------------------------------
    def axis_size(self, axis: str) -> int:
        return int(dict(self.mesh.shape).get(axis, 1))

    def _present(self, axis: str) -> bool:
        return self.axis_size(axis) > 1

    @property
    def fsdp(self) -> int:
        return self.axis_size(self.fsdp_axis)

    @property
    def tp(self) -> int:
        return self.axis_size(self.tp_axis)

    def signature(self) -> Tuple:
        """Trace-identity of this layout: mesh topology + axis naming +
        rules — what a compiled-step cache key folds in so a mesh or
        rule change retraces instead of reusing a stale executable."""
        return self._sig

    # -- specs -------------------------------------------------------------
    def batch_spec(self) -> P:
        """Batch axis 0 splits over every data-parallel axis present:
        under FSDP each fsdp rank consumes its own micro-shard (ZeRO is
        data parallelism), so the batch spec is ``(data, fsdp)``."""
        axes = [a for a in (self.data_axis, self.fsdp_axis)
                if self._present(a)]
        if not axes:
            return P()
        return P(tuple(axes) if len(axes) > 1 else axes[0])

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def batch_spec_for(self, shape, batch_dim: int = 0) -> P:
        """The batch spec applied to dimension `batch_dim` of `shape`
        (stacked scan-window leaves carry (n_micro, B, ...) — the batch
        is axis 1 there), degraded to replication when the dimension
        does not divide the data×fsdp extent."""
        if not shape or batch_dim >= len(shape):
            return P()
        axes = [a for a in (self.data_axis, self.fsdp_axis)
                if self._present(a)]
        if not axes:
            return P()
        entries = [None] * len(shape)
        entries[batch_dim] = tuple(axes) if len(axes) > 1 else axes[0]
        return self._fit(tuple(entries), shape)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def _fit(self, spec_entries, shape) -> P:
        """Drop spec axes the shape cannot honor (missing from the mesh,
        size 1, or not dividing the dimension) — an ill-fitting axis
        replicates that dimension rather than erroring, so one layout
        serves every mesh class."""
        out = []
        for dim, entry in zip(shape, spec_entries):
            if entry is None:
                out.append(None)
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            kept, whole = [], 1
            for a in axes:
                sz = self.axis_size(a)
                if sz > 1 and int(dim) % (whole * sz) == 0:
                    kept.append(a)
                    whole *= sz
            out.append(tuple(kept) if len(kept) > 1
                       else (kept[0] if kept else None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def embedding_spec(self, shape) -> P:
        """Embedding tables shard the vocab axis over fsdp×tp (the
        SNIPPETS pattern): both model axes carve the one huge dimension,
        lookups gather only the owning shard's rows."""
        if len(shape) < 1:
            return P()
        return self._fit(((self.fsdp_axis, self.tp_axis),)
                         + (None,) * (len(shape) - 1), shape)

    def linear_spec(self, shape) -> P:
        """Dense ``(out, in)`` weights: column-parallel over ``tp`` on
        the output dim (Megatron), ``fsdp``-sharded on the input dim —
        each chip owns an (out/tp, in/fsdp) tile."""
        if len(shape) != 2:
            return self.sheet_spec(shape)
        return self._fit((self.tp_axis, self.fsdp_axis), shape)

    def sheet_spec(self, shape) -> P:
        """The everything-else default: sheet-shard the largest
        fsdp-divisible dimension over ``fsdp``; replicate when nothing
        divides (biases, scalars, odd shapes)."""
        fsdp = self.fsdp
        if fsdp <= 1 or not shape:
            return P()
        best = None
        for i, d in enumerate(shape):
            if _dim_divisible(int(d), fsdp):
                if best is None or int(d) > int(shape[best]):
                    best = i
        if best is None:
            return P()
        entries = [None] * len(shape)
        entries[best] = self.fsdp_axis
        return self._fit(tuple(entries), shape)

    def param_spec(self, name: str, shape, dtype=None,
                   kind: Optional[str] = None,
                   hook_spec: Optional[P] = None) -> P:
        """One parameter's PartitionSpec under the documented resolution
        order: rules > Block hook > kind default > fsdp sheet."""
        for frag, spec in self.rules.items():
            if frag in name:
                return self._fit(tuple(spec) + (None,) *
                                 (len(shape) - len(tuple(spec))), shape)
        if hook_spec is not None:
            return self._fit(tuple(hook_spec) + (None,) *
                             (len(shape) - len(tuple(hook_spec))), shape)
        if kind == "embedding":
            return self.embedding_spec(shape)
        if kind == "linear":
            return self.linear_spec(shape)
        return self.sheet_spec(shape)

    def compute_spec(self, spec: P) -> P:
        """The spec a parameter COMPUTES under: its storage spec with the
        fsdp axis removed.  FSDP stores sheet-sharded but consumes whole
        (tp splits stay — they are the intra-layer compute layout); the
        sharded step constrains each parameter to this spec at its use
        site, which is the explicit just-in-time all-gather, and
        constrains gradients back to the storage spec (the
        reduce-scatter).  Keeping the gather explicit also sidesteps an
        XLA:SPMD partitioner unsoundness: differentiating a stacked
        matmul whose weight carries BOTH tp and fsdp while the batch is
        fsdp-sharded miscompiles the weight gradient (observed on
        XLA:CPU, jax 0.4.37) unless the operand is resharded before the
        dot."""
        out = []
        for entry in tuple(spec):
            if entry is None:
                out.append(None)
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            kept = [a for a in axes if a != self.fsdp_axis]
            out.append(tuple(kept) if len(kept) > 1
                       else (kept[0] if kept else None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)

    def state_spec(self, param_spec: P, shape) -> P:
        """Optimizer slot state lives ZeRO-style on its parameter's
        shards (same-shape moments inherit the spec verbatim); shapes
        that differ from the parameter fall back to the sheet default."""
        entries = tuple(param_spec)
        if len(entries) <= len(shape):
            return self._fit(entries + (None,) * (len(shape) -
                                                  len(entries)), shape)
        return self.sheet_spec(shape)

    # -- block resolution --------------------------------------------------
    def resolve(self, block=None, params: Optional[Dict[str, Any]] = None
                ) -> Dict[str, P]:
        """{structural name: PartitionSpec} for every parameter.

        With a ``block``, walks the tree collecting each sub-block's
        :meth:`sharding_spec` hook result and the kind defaults
        (:data:`_BLOCK_PARAM_KINDS`); with a bare ``params`` mapping
        (name -> array-like), only rules + shape defaults apply.
        """
        hook_specs: Dict[int, P] = {}
        kinds: Dict[int, str] = {}
        named = {}
        if block is not None:
            self._walk(block, hook_specs, kinds)
            for name, p in block.collect_params().items():
                named[name] = p
        elif params is not None:
            named = dict(params)
        out: Dict[str, P] = {}
        for name, p in named.items():
            shape = tuple(getattr(p, "shape", ()) or ())
            dtype = getattr(p, "dtype", None)
            out[name] = self.param_spec(
                name, shape, dtype, kind=kinds.get(id(p)),
                hook_spec=hook_specs.get(id(p)))
        return out

    def _walk(self, block, hook_specs: Dict[int, P],
              kinds: Dict[int, str]) -> None:
        by_kind = _BLOCK_PARAM_KINDS.get(type(block).__name__)
        if by_kind:
            for attr, kind in by_kind.items():
                p = block._reg_params.get(attr)
                if p is not None:
                    kinds[id(p)] = kind
        hook = getattr(block, "sharding_spec", None)
        if callable(hook):
            declared = hook(self) or {}
            for key, spec in declared.items():
                p = key if not isinstance(key, str) \
                    else block._reg_params.get(key)
                if p is not None and spec is not None:
                    hook_specs[id(p)] = spec
        for child in block._children.values():
            self._walk(child, hook_specs, kinds)


# ---------------------------------------------------------------------------
# placement (the device_put half of the old shard_params_tp, now shared)
# ---------------------------------------------------------------------------


def place_value(value, sharding: NamedSharding):
    """Place one (host or device) value onto `sharding`.  Multi-host:
    every process holds the SAME full value (same-seed init/broadcast),
    so the global array assembles from local slices instead of paying a
    cross-host device_put."""
    if getattr(value, "sharding", None) == sharding:
        return value
    if jax.process_count() > 1:
        host_v = _np.asarray(value)
        return jax.make_array_from_callback(
            host_v.shape, sharding, lambda idx, hv=host_v: hv[idx])
    return jax.device_put(value, sharding)


def shard_params(param_values: Dict[str, jax.Array],
                 layout: SpecLayout,
                 specs: Optional[Dict[str, P]] = None
                 ) -> Dict[str, jax.Array]:
    """Place a name->array mapping onto the layout's resolved specs."""
    specs = specs or layout.resolve(params=param_values)
    return {name: place_value(v, layout.sharding(specs.get(name, P())))
            for name, v in param_values.items()}


# ---------------------------------------------------------------------------
# the folded-in TP-only entry point (parallel/mesh.py keeps a thin alias)
# ---------------------------------------------------------------------------


def tp_alternation_specs(param_values: Dict[str, Any], mesh: Mesh,
                         tp_axis: str = "tp",
                         rules: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, P]:
    """The legacy ``shard_params_tp`` layout as pure specs: explicit
    rules (unmatched params replicate), else alternate column-parallel
    ``(tp, None)`` / row-parallel ``(None, tp)`` for consecutive 2-D
    '.weight' params; biases and everything else replicate."""
    tp = int(dict(mesh.shape).get(tp_axis, 1))
    specs: Dict[str, P] = {}
    col = True
    for name, v in param_values.items():
        if rules is not None:
            spec = P()
            for frag, s in rules.items():
                if frag in name:
                    spec = s
                    break
        elif tp > 1 and name.endswith("weight") and \
                getattr(v, "ndim", len(getattr(v, "shape", ()))) == 2:
            spec = P(tp_axis, None) if col else P(None, tp_axis)
            col = not col
        else:
            spec = P()
        specs[name] = spec
    return specs


def shard_params_tp(param_values: Dict[str, jax.Array], mesh: Mesh,
                    tp_axis: str = "tp",
                    rules: Optional[Dict[str, Any]] = None):
    """Deprecated TP-only placement (the pre-SpecLayout entry point).

    Kept as a thin alias over :func:`tp_alternation_specs` +
    :func:`place_value` with the exact legacy semantics; new code should
    build a :class:`SpecLayout` and use :func:`shard_params` (one source
    of truth for parameter shardings, fsdp included).
    """
    specs = tp_alternation_specs(param_values, mesh, tp_axis, rules)
    return {name: place_value(v, NamedSharding(mesh, specs[name]))
            for name, v in param_values.items()}


# ---------------------------------------------------------------------------
# env-driven construction (MX_MESH_AXES / MX_FSDP)
# ---------------------------------------------------------------------------


def parse_mesh_axes(text: str, fsdp_override: Optional[int] = None
                    ) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """Parse ``MX_MESH_AXES`` — comma-separated ``name[=size]`` tokens,
    e.g. ``data,fsdp=2,tp=2``.  Unsized axes default to -1 (inferred)
    for the data axis and 2 for model axes; ``fsdp_override`` (the
    MX_FSDP knob) wins for the fsdp axis."""
    axes, sizes = [], []
    for tok in (text or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        if "=" in tok:
            name, _, sz = tok.partition("=")
            name = name.strip()
            size = int(sz)
        else:
            name = tok
            size = -1 if name in ("data", "dp", "batch") else 2
        if name == "fsdp" and fsdp_override is not None:
            size = int(fsdp_override)
        if size != -1 and size < 1:
            # MX_FSDP=0 is the conventional 'off' spelling: a zero (or
            # negative) axis degrades to size 1 — the axis drops out of
            # every spec — instead of a ZeroDivisionError in make_mesh
            size = 1
        axes.append(name)
        sizes.append(size)
    if not axes:
        raise ValueError("MX_MESH_AXES is empty")
    return tuple(axes), tuple(sizes)


def mesh_from_env(devices=None) -> Optional[Mesh]:
    """Mesh described by MX_MESH_AXES/MX_FSDP, or None when unset.

    ``MX_FSDP=N`` alone (without MX_MESH_AXES) means ``data,fsdp=N``.
    """
    from ..base import get_env
    axes_text = get_env("MX_MESH_AXES")
    fsdp = get_env("MX_FSDP")
    fsdp_n = None
    if fsdp:
        try:
            fsdp_n = int(fsdp)
        except ValueError:
            fsdp_n = None
    if not axes_text:
        if not fsdp_n or fsdp_n <= 1:
            return None
        axes_text = "data,fsdp"
    from .mesh import make_mesh
    axes, sizes = parse_mesh_axes(axes_text, fsdp_n)
    return make_mesh(axes=axes, shape=sizes, devices=devices)


def mesh_for_world(world: int, devices=None) -> Mesh:
    """Mesh for an elastic incarnation with ``world`` data-parallel
    participants (ISSUE 16 resize glue): the env-described axes
    (MX_MESH_AXES/MX_FSDP, default plain ``data``) with the data axis
    forced to ``world``.  Model axes keep their configured sizes while
    the mesh still fits the visible devices; an axis that no longer
    fits degrades to 1 — it drops out of every spec — rather than
    failing the resize.  Pairs with
    ``checkpoint.resume_or_init(mesh=mesh_for_world(n))``: the saved
    per-leaf spec sidecar re-shards the old world's state onto this
    mesh by axis NAME, whatever size the old world was."""
    world = int(world)
    if world < 1:
        raise ValueError("mesh_for_world needs world >= 1, got %d"
                         % world)
    if devices is None:
        devices = jax.devices()
    from ..base import get_env
    fsdp = get_env("MX_FSDP")
    try:
        fsdp_n = int(fsdp) if fsdp else None
    except ValueError:
        fsdp_n = None
    axes_text = get_env("MX_MESH_AXES")
    if not axes_text:
        axes_text = "data,fsdp" if fsdp_n and fsdp_n > 1 else "data"
    axes, sizes = parse_mesh_axes(axes_text, fsdp_n)
    sizes = list(sizes)
    di = next((i for i, a in enumerate(axes)
               if a in ("data", "dp", "batch")), 0)
    sizes[di] = world

    def _prod(xs):
        p = 1
        for x in xs:
            p *= max(1, int(x))
        return p
    # degrade model axes innermost-first until the mesh fits
    for i in range(len(sizes) - 1, -1, -1):
        if _prod(sizes) <= len(devices):
            break
        if i != di:
            sizes[i] = 1
    if _prod(sizes) > len(devices):
        raise ValueError(
            "mesh_for_world: world %d needs %d devices, only %d visible"
            % (world, _prod(sizes), len(devices)))
    from .mesh import make_mesh
    return make_mesh(axes=axes, shape=sizes, devices=devices)


def layout_from_env(devices=None, rules=None) -> Optional[SpecLayout]:
    """SpecLayout from the env knobs, or None when they are unset (the
    replicated default).  The hook the compiled-step lane consults when
    no explicit layout is passed."""
    mesh = mesh_from_env(devices)
    if mesh is None:
        return None
    return SpecLayout.infer(mesh, rules=rules)
