"""Fleet telemetry plane: cross-process metrics aggregation, straggler
and SLO detection, and the merged-snapshot API (ISSUE 12 tentpole).

PR 8/10 gave every *process* deep observability; nothing could see the
*fleet*: the supervisor read heartbeat files one rank at a time, serve
replicas each answered their own METRICS verb, and no component merged,
ranked or alarmed across them.  This module is that missing plane — the
signal source ROADMAP items 2 and 3 (elastic membership, serve
router/autoscaler) consume ready-made instead of re-inventing scraping
(the multi-tenant serving control loop of TensorFlow Serving, arxiv
1605.08695, and the per-node visibility the original parameter-server
design assumed, arxiv 1512.01274 — PAPERS.md):

* **FleetCollector** — periodically scrapes every registered
  :class:`FleetMember`: serve replicas and PS servers over their
  METRICS wire verb (``fmt='json'``: the registry snapshot), training
  workers from their heartbeat files' JSON payload (the degraded
  fallback — a worker has no wire server, but its flight recorder
  already rides the beat).  Per-process snapshots merge into fleet
  rollups with exact algebra: counters SUM (per-member restart resets
  are rebased, never double-counted and never backwards), gauges keep
  per-member values plus min/mean/max, histograms merge BUCKET-WISE
  (the registry's cumulative-bucket exposition makes the merge exact;
  mismatched boundaries are rejected loudly).  Snapshots retain in a
  bounded ring (``MX_FLEET_RING``).

* **Detectors** — a straggler/skew detector for training (windowed
  per-rank step duration vs the fleet lower-median; a rank over
  ``MX_FLEET_STRAGGLER_FACTOR``x is flagged with its dominant phase —
  ``fleet.stragglers`` gauge + flight-recorder event + structured
  warning) and an SLO tracker for serving (rolling p50/p99 from the
  merged ``MX_FLEET_SLO_PHASES`` histograms, rejection-rate and
  queue-depth burn vs ``MX_FLEET_SLO_*`` targets →
  ``fleet.slo_burn{slo=...}`` gauges with LATCHED breach events).

* **Three faces** — the FLEET wire verb (merged snapshot as a typed
  JSN payload; the future router/autoscaler API), a Prometheus
  federation endpoint (one scrape = the whole fleet, every member's
  series re-labeled ``role``/``rank``/``model``), and
  ``tools/fleet_top.py`` (live terminal dashboard replacing ad-hoc
  reading of N heartbeat files).  ``tools/launch.py`` embeds a
  collector so every supervised job gets the plane for free; its crash
  dumps gain a ``fleet`` section (the last merged snapshot).

The scrape/merge loop is an mxlint hot-path root: it runs forever next
to training/serving processes, so it must never sync a device (this
module imports no jax and no numpy).  Timing follows the repo clock
discipline — logic on :func:`mxnet_tpu.fault.now`, wall stamps only for
humans reading dumps.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import fault as _fault
from . import telemetry as _telemetry
from .base import MXNetError, get_env
from .kvstore.server import send_msg, recv_msg
from .kvstore.wire_codec import decode_json, decode_text, encode_json, \
    encode_text
from .kvstore.wire_verbs import declare_verbs

__all__ = [
    "SCHEMA", "FleetMergeError", "FleetMember", "FleetCollector",
    "StragglerDetector", "SLOTracker",
    "merge_bucket_maps", "quantile_from_buckets", "merge_snapshots",
    "serve_fleet", "fetch_fleet", "fetch_metrics", "replica_signals",
]

# FLEET payload schema.  2 (ISSUE 17): member rows carry their scrape
# ``addr``, making the snapshot directly router-consumable — the router
# maps per-member gauges back to the replica address it forwards to.
# 3 (ISSUE 20): a ``models`` section rolls model-labeled serve.*
# counters up per co-hosted model (multi-model replicas), so per-model
# traffic is first-class in the merged snapshot.
SCHEMA = 3

# The fleet wire surface, DECLARED (ISSUE 11 contract): mxlint's
# wire-verb-exhaustive rule pairs every emitted verb with an entry
# here, checks this file handles it, and that named codecs have
# encode_*/decode_* pairs in kvstore/wire_codec.py.  Read-only by
# construction — the collector never mutates a member.
WIRE_VERBS = declare_verbs("fleet", {
    # merged fleet snapshot as one typed JSN payload: THE api the
    # coming serve router/autoscaler (ROADMAP item 3) call
    "FLEET": {"semantics": "idempotent", "replay": "bypass",
              "codec": "json", "mutates": ()},
    # whole-fleet federation exposition (or the collector's own
    # registry as json) — same contract as the serve/kvstore scrape
    "METRICS": {"semantics": "idempotent", "replay": "bypass",
                "codec": "text", "mutates": ()},
}, role="collector", handler="serve_fleet.Handler.handle")


class FleetMergeError(MXNetError):
    """Merge-algebra violation (e.g. histogram boundary mismatch)."""


# ---------------------------------------------------------------------------
# merge algebra (pure; unit-tested in tests/test_fleet.py)
# ---------------------------------------------------------------------------

def _entry_name(key: str, entry: Dict[str, Any]) -> str:
    return entry.get("name") or key.split("{", 1)[0]


def merge_bucket_maps(maps: Sequence[Dict[str, Any]],
                      name: str = "?") -> Dict[str, int]:
    """Bucket-wise merge of cumulative histogram bucket maps.

    Exact by construction: cumulative counts on IDENTICAL boundaries
    add; any boundary mismatch means the members were configured
    differently and a silent merge would fabricate quantiles — rejected
    with a :class:`FleetMergeError` naming the instrument."""
    maps = [m for m in maps if m]
    if not maps:
        return {}
    keys = set(maps[0])
    for m in maps[1:]:
        if set(m) != keys:
            raise FleetMergeError(
                "fleet: histogram %r bucket boundaries differ across "
                "members (%r vs %r) - refusing to merge mismatched "
                "buckets" % (name, sorted(keys), sorted(m)))
    return {k: int(sum(m[k] for m in maps)) for k in keys}


def _sorted_bounds(buckets: Dict[str, Any]) -> List[Tuple[float, str]]:
    out = []
    for k in buckets:
        if k == "+Inf":
            continue
        try:
            out.append((float(k), k))
        except ValueError:
            continue
    out.sort()
    return out


def quantile_from_buckets(buckets: Dict[str, Any], q: float) -> float:
    """q-quantile from a cumulative bucket map, upper-bound convention:
    the smallest bucket boundary whose cumulative count covers q of the
    total.  Both a merged histogram and its members use the same
    convention, so a correct merge reproduces per-member quantiles to
    within one bucket boundary exactly.

    Mass above the TOP bound reports the largest finite boundary (the
    Prometheus ``histogram_quantile`` convention) — an infinity here
    would ride the FLEET/``/fleet.json`` payloads as the non-RFC
    ``Infinity`` token and break every non-Python consumer."""
    total = buckets.get("+Inf", 0) or 0
    if total <= 0:
        return 0.0
    want = q * total
    bounds = _sorted_bounds(buckets)
    for bound, key in bounds:
        if buckets[key] >= want:
            return bound
    return bounds[-1][0] if bounds else 0.0


def merge_snapshots(member_snaps: Dict[str, Dict[str, Any]],
                    include_counters: bool = True) -> Dict[str, Any]:
    """Merge per-member registry snapshots (``Registry.snapshot()``
    dicts keyed by member id) into one fleet rollup:

    counters  -> ``{"total", "per_member"}`` (summed RAW values; the
                 collector passes ``include_counters=False`` and
                 substitutes its restart-REBASED running totals — use
                 this pure form only when no member ever restarts)
    gauges    -> ``{"per_member", "min", "mean", "max"}``
    histograms-> ``{"buckets", "count", "sum", "p50", "p99"}``
                 (bucket-wise exact merge)

    Pure function of its inputs — restart rebasing is the collector's
    job (it owns the per-member history); tests drive this directly."""
    counters: Dict[str, Dict[str, Any]] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for mid in sorted(member_snaps):
        snap = member_snaps[mid] or {}
        for key, entry in snap.items():
            if not isinstance(entry, dict):
                continue
            kind = entry.get("type")
            if kind == "counter":
                if not include_counters:
                    continue
                slot = counters.setdefault(key, {"total": 0,
                                                 "per_member": {}})
                val = entry.get("value", 0) or 0
                slot["per_member"][mid] = val
                slot["total"] += val
            elif kind == "gauge":
                slot = gauges.setdefault(key, {"per_member": {}})
                slot["per_member"][mid] = entry.get("value", 0) or 0
            elif kind == "histogram":
                slot = hists.setdefault(key, {"_maps": [], "count": 0,
                                              "sum": 0.0})
                slot["_maps"].append(entry.get("buckets") or {})
                slot["count"] += entry.get("count", 0) or 0
                slot["sum"] += entry.get("sum", 0.0) or 0.0
    for slot in gauges.values():
        vals = list(slot["per_member"].values())
        slot["min"] = min(vals) if vals else 0
        slot["max"] = max(vals) if vals else 0
        slot["mean"] = (sum(vals) / len(vals)) if vals else 0.0
    for key, slot in hists.items():
        merged = merge_bucket_maps(slot.pop("_maps"), name=key)
        slot["buckets"] = merged
        slot["p50"] = quantile_from_buckets(merged, 0.50)
        slot["p99"] = quantile_from_buckets(merged, 0.99)
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def _lower_median(values: Sequence[float]) -> float:
    """Median with the LOWER element on even counts: with only two
    workers, [1x, 3x]'s lower median is 1x, so a 3x-slow rank still
    reads as 3x over 'the fleet' instead of 1.5x over the midpoint —
    exactly the two-rank chaos case the acceptance pins."""
    vs = sorted(values)
    if not vs:
        return 0.0
    return vs[(len(vs) - 1) // 2]


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

class StragglerDetector:
    """Training straggler/skew detection over a sliding window.

    Per scrape round, each worker contributes its step duration
    (``1/steps_per_sec`` from the beat; falling back to the summed
    per-phase seconds) and its per-phase breakdown.  A worker whose
    windowed mean step duration exceeds ``factor`` x the fleet
    lower-median is a straggler; the finding names the member and its
    dominant phase (``data_wait`` share is the classic input-bound
    signature), so the operator knows WHAT is slow, not just WHO."""

    def __init__(self, factor: Optional[float] = None,
                 window: Optional[int] = None, min_members: int = 2):
        if factor is None:
            factor = get_env("MX_FLEET_STRAGGLER_FACTOR", 2.0, float) \
                or 2.0
        if window is None:
            window = get_env("MX_FLEET_WINDOW", 5, int) or 5
        self.factor = float(factor)
        self.window = max(1, int(window))
        self.min_members = max(2, int(min_members))
        self._hist: Dict[str, deque] = {}
        self._missed: Dict[str, int] = {}
        # guards _hist/_missed: the collector thread updates them every
        # scrape round while an elastic retire() (main/supervisor
        # thread) may drop a departed member mid-round
        self._guard = threading.Lock()

    def update(self, worker_stats: Dict[str, Dict[str, Any]]
               ) -> List[Dict[str, Any]]:
        """One scrape round of ``{member_id: {"step_seconds", "phases"}}``
        -> the current straggler findings (possibly empty)."""
        reported = set()
        with self._guard:
            for mid, st in worker_stats.items():
                dur = st.get("step_seconds")
                if dur is None or dur <= 0:
                    continue
                dq = self._hist.setdefault(mid,
                                           deque(maxlen=self.window))
                dq.append((float(dur), dict(st.get("phases") or {})))
                self._missed[mid] = 0
                reported.add(mid)
            # a member that stopped reporting a USABLE step duration —
            # absent, or present with an empty/unreadable payload — falls
            # out of the comparison, but only after a full window of
            # misses: one transient scrape failure must not reset a slow
            # rank's accumulated history (it would oscillate out of
            # detection exactly when it matters), while a permanently
            # silent one must not stay flagged on a frozen mean forever
            for mid in list(self._hist):
                if mid not in reported:
                    self._missed[mid] = self._missed.get(mid, 0) + 1
                    if self._missed[mid] > self.window:
                        self._hist.pop(mid)
                        self._missed.pop(mid, None)
            means = {mid: sum(d for d, _p in dq) / len(dq)
                     for mid, dq in self._hist.items() if dq}
            if len(means) < self.min_members:
                return []
            med = _lower_median(list(means.values()))
            if med <= 0:
                return []
            out = []
            for mid, mean_dur in sorted(means.items()):
                if mean_dur <= self.factor * med:
                    continue
                phases: Dict[str, float] = {}
                for _d, p in self._hist[mid]:
                    for k, v in p.items():
                        phases[k] = phases.get(k, 0.0) + float(v)
                total = sum(phases.values())
                dom, share = None, 0.0
                if phases:
                    dom = max(phases, key=lambda k: phases[k])
                    share = phases[dom] / total if total > 0 else 0.0
                out.append({"member": mid,
                            "step_seconds": round(mean_dur, 6),
                            "fleet_median_seconds": round(med, 6),
                            "ratio": round(mean_dur / med, 3),
                            "dominant_phase": dom,
                            "dominant_share": round(share, 4)})
            return out

    def retire(self, mid: str) -> None:
        """Drop a member from straggler tracking IMMEDIATELY (elastic
        membership, ISSUE 16): a worker that sent LEAVE — or was evicted
        from the kvstore membership table — is gone by protocol, not
        merely silent, so it must not sit in the window as a frozen mean
        (a false straggler flag on every voluntary shrink) or burn the
        full miss-window aging out."""
        with self._guard:
            self._hist.pop(mid, None)
            self._missed.pop(mid, None)


class SLOTracker:
    """Serving SLO burn over a sliding window of scrape deltas.

    Latency comes from the fleet-merged ``MX_FLEET_SLO_PHASES``
    histograms — per-round bucket DELTAS accumulate into a rolling
    window distribution whose p50/p99 are compared against the declared
    millisecond targets; rejection rate from merged ``serve.rejected``
    / ``serve.requests`` counter deltas; queue depth from the mean
    merged ``serve.queue_rows`` gauge.  Burn = observed/target; a burn
    over 1.0 LATCHES a breach event (it stays raised until
    :meth:`reset` — an alert that un-fires the moment load dips is an
    alert nobody sees)."""

    def __init__(self, window: Optional[int] = None,
                 targets: Optional[Dict[str, float]] = None):
        if window is None:
            window = get_env("MX_FLEET_WINDOW", 5, int) or 5
        self.window = max(1, int(window))
        if targets is None:
            targets = {}
            for slo, env in (("p50_latency", "MX_FLEET_SLO_P50_MS"),
                             ("p99_latency", "MX_FLEET_SLO_P99_MS"),
                             ("rejection_rate",
                              "MX_FLEET_SLO_REJECT_RATE"),
                             ("queue_depth", "MX_FLEET_SLO_QUEUE")):
                raw = get_env(env, "")
                if raw not in (None, ""):
                    try:
                        targets[slo] = float(raw)
                    except (TypeError, ValueError):
                        pass
        self.targets = {k: float(v) for k, v in targets.items()
                        if v and v > 0}
        # leaf lock: update() runs on the collector thread while
        # reset()/breach reads come from operators (main thread)
        self._lock = threading.Lock()
        self._lat = deque(maxlen=self.window)    # bucket-delta maps
        self._rej = deque(maxlen=self.window)    # (rejected, offered)
        self._breached: Dict[str, Dict[str, Any]] = {}

    @property
    def breached(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._breached.items()}

    def reset(self) -> None:
        """Un-latch every breach (operator acknowledged)."""
        with self._lock:
            self._breached = {}

    def update(self, latency_delta: Dict[str, int],
               rejected_delta: float, offered_delta: float,
               queue_depth: float) -> Dict[str, Any]:
        with self._lock:
            # empty rounds append too: the window must AGE OUT during
            # idle, or a spike's p99 burn would read hot forever on a
            # fleet serving no traffic (merge_bucket_maps drops empties)
            self._lat.append(latency_delta or {})
            self._rej.append((max(0.0, rejected_delta),
                              max(0.0, offered_delta)))
            window_map = merge_bucket_maps(list(self._lat),
                                           name="slo_latency_window") \
                if self._lat else {}
            rej = sum(r for r, _o in self._rej)
            off = sum(o for _r, o in self._rej)
        p50_ms = quantile_from_buckets(window_map, 0.50) * 1e3
        p99_ms = quantile_from_buckets(window_map, 0.99) * 1e3
        reject_rate = rej / off if off > 0 else 0.0
        observed = {"p50_latency": p50_ms, "p99_latency": p99_ms,
                    "rejection_rate": reject_rate,
                    "queue_depth": float(queue_depth)}
        burn: Dict[str, float] = {}
        with self._lock:
            for slo, target in self.targets.items():
                b = observed[slo] / target
                burn[slo] = round(b, 4)
                if b > 1.0 and slo not in self._breached:
                    self._breached[slo] = {
                        "slo": slo, "burn": round(b, 4),
                        "observed": round(observed[slo], 4),
                        "target": target, "ts": _fault.now()}
            breached = {k: dict(v) for k, v in self._breached.items()}
        return {"p50_ms": round(p50_ms, 4), "p99_ms": round(p99_ms, 4),
                "rejection_rate": round(reject_rate, 6),
                "queue_depth": round(float(queue_depth), 3),
                "targets": dict(self.targets), "burn": burn,
                "breached": breached}


# ---------------------------------------------------------------------------
# members + wire scraping
# ---------------------------------------------------------------------------

class FleetMember:
    """One scrape target: ``addr`` (host:port) members answer the
    METRICS wire verb; ``heartbeat`` members are read from their
    liveness file's JSON payload (degraded fallback — no wire server
    in a training worker)."""

    __slots__ = ("role", "rank", "addr", "heartbeat", "model")

    def __init__(self, role: str, rank, addr: Optional[str] = None,
                 heartbeat: Optional[str] = None,
                 model: Optional[str] = None):
        if not addr and not heartbeat:
            raise MXNetError("FleetMember %s:%s needs an addr (wire "
                             "METRICS) or a heartbeat file path"
                             % (role, rank))
        self.role = str(role)
        self.rank = str(rank)
        self.addr = addr
        self.heartbeat = heartbeat
        self.model = model

    @property
    def key(self) -> str:
        return "%s:%s" % (self.role, self.rank)

    def __repr__(self):
        return "FleetMember(%s, %s)" % (
            self.key, self.addr or self.heartbeat)


def fetch_metrics(addr: str, fmt: str = "json", timeout: float = 5.0):
    """Scrape one member's METRICS verb (serve replica, PS server, or a
    fleet collector's wire server).  ``fmt='json'`` returns the decoded
    registry-snapshot dict; ``'prometheus'`` the exposition text."""
    with _telemetry.rpc_span("fleet.scrape.METRICS") as span:
        span.event("scrape", addr=addr, fmt=fmt)
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=timeout)
        try:
            sock.settimeout(timeout)
            send_msg(sock, ("METRICS", fmt))
            ok, payload = recv_msg(sock, timeout=timeout)
        finally:
            try:
                sock.close()
            except OSError:
                pass
    if not ok:
        raise MXNetError("fleet: %s answered METRICS: %s"
                         % (addr, payload))
    text = decode_text(payload)
    return json.loads(text) if fmt == "json" else text


def fetch_fleet(addr: str, timeout: float = 5.0) -> Dict[str, Any]:
    """Fetch the merged fleet snapshot over the FLEET wire verb — the
    call the serve router/autoscaler (ROADMAP item 3) and
    tools/fleet_top.py make."""
    with _telemetry.rpc_span("fleet.client.FLEET") as span:
        span.event("fetch", addr=addr)
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=timeout)
        try:
            sock.settimeout(timeout)
            send_msg(sock, ("FLEET",))
            ok, payload = recv_msg(sock, timeout=timeout)
        finally:
            try:
                sock.close()
            except OSError:
                pass
    if not ok:
        raise MXNetError("fleet: %s answered FLEET: %s" % (addr, payload))
    return decode_json(payload)


def replica_signals(snapshot: Optional[Dict[str, Any]],
                    role: str = "serve") -> Dict[str, Dict[str, Any]]:
    """The router-consumable signal surface (ISSUE 17): one merged
    FLEET snapshot -> ``{replica addr: load signals}`` for every
    ``role`` member that carries a scrape address.

    Pure function of the schema-2 payload, so the router, the
    autoscaler, and tests all read the SAME projection: per-replica
    queue depth (``serve.queue_rows`` + decode admission queue), decode
    slot occupancy, KV-pool admission headroom, and cumulative
    rejections (the caller differences these for burn).  Members whose
    row predates schema 2 (no ``addr``) are skipped — a router must
    never route to an address it cannot name."""
    out: Dict[str, Dict[str, Any]] = {}
    if not isinstance(snapshot, dict):
        return out
    gauges = snapshot.get("gauges") or {}
    counters = snapshot.get("counters") or {}

    def _per_member(table, name):
        slot = table.get(name) or {}
        return slot.get("per_member") or {}

    queue = _per_member(gauges, "serve.queue_rows")
    dqueue = _per_member(gauges, "serve.decode.queue")
    active = _per_member(gauges, "serve.decode.active_slots")
    occupancy = _per_member(gauges, "serve.decode.slot_occupancy")
    headroom = _per_member(gauges, "serve.decode.kv_headroom_bytes")
    # paged replicas (ISSUE 18) additionally publish page-level
    # headroom + sharing savings; flat replicas simply lack the gauges
    # (keys default to 0/absent — same schema 2, router math unchanged:
    # kv_headroom_bytes already means "admission headroom in bytes" on
    # both engines)
    free_pages = _per_member(gauges, "serve.decode.kv_free_pages")
    shared_saved = _per_member(gauges,
                               "serve.decode.kv_shared_saved_bytes")
    rejected = _per_member(counters, "serve.rejected")
    d_rejected = _per_member(counters, "serve.decode.rejected")
    for key, meta in (snapshot.get("members") or {}).items():
        if not isinstance(meta, dict) or meta.get("role") != role:
            continue
        addr = meta.get("addr")
        if not addr:
            continue
        out[str(addr)] = {
            "member": key,
            "present": bool(meta.get("present")),
            "queue_rows": queue.get(key, 0) or 0,
            "decode_queue": dqueue.get(key, 0) or 0,
            "active_slots": active.get(key, 0) or 0,
            "slot_occupancy": occupancy.get(key, 0.0) or 0.0,
            "kv_headroom_bytes": headroom.get(key, 0) or 0,
            "kv_free_pages": free_pages.get(key),
            "kv_shared_saved_bytes": shared_saved.get(key, 0) or 0,
            "rejected": (rejected.get(key, 0) or 0)
            + (d_rejected.get(key, 0) or 0),
        }
    return out


# ---------------------------------------------------------------------------
# the collector
# ---------------------------------------------------------------------------

class _MemberState:
    """Per-member scrape history the merge algebra needs: raw last
    snapshot, counter rebase offsets (restart discontinuities), and the
    previous histogram cumulative maps (window deltas)."""

    __slots__ = ("present", "absent_scrapes", "source", "age", "model",
                 "last_snap", "counter_raw", "counter_base",
                 "prev_hists", "malformed")

    def __init__(self):
        self.present = False
        self.absent_scrapes = 0
        self.source = None
        self.age: Optional[float] = None
        self.model: Optional[str] = None
        self.last_snap: Dict[str, Any] = {}
        self.counter_raw: Dict[str, float] = {}
        self.counter_base: Dict[str, float] = {}
        self.prev_hists: Dict[str, Dict[str, int]] = {}
        self.malformed = 0


# the process's most recently active collector: crash dumps embed its
# last merged snapshot as the `fleet` section, so a post-mortem shows
# what the REST of the job was doing when this process died
_active: List[Optional["FleetCollector"]] = [None]


def _fleet_crash_section():
    c = _active[0]
    return c.snapshot() if c is not None else None


_telemetry.register_crash_section("fleet", _fleet_crash_section)


class FleetCollector:
    """Scrape -> merge -> detect loop over a registered member set.

    Lock discipline: ``_lock`` is a leaf guarding the member/state/ring
    tables only — scraping (socket IO) happens OUTSIDE it, merge is
    pure, and registry instrument updates take their own leaf locks
    after ``_lock`` is released."""

    def __init__(self, members: Sequence[FleetMember] = (),
                 interval: Optional[float] = None,
                 ring: Optional[int] = None,
                 window: Optional[int] = None,
                 stale_after: Optional[float] = None,
                 straggler_factor: Optional[float] = None,
                 slo_targets: Optional[Dict[str, float]] = None,
                 scrape_timeout: float = 5.0, logger=None):
        if interval is None:
            interval = get_env("MX_FLEET_INTERVAL", 2.0, float) or 2.0
        self.interval = float(interval)
        if ring is None:
            ring = get_env("MX_FLEET_RING", 120, int) or 120
        if stale_after is None:
            # auto floor is 30s, not a couple of intervals: heartbeats
            # are rewritten per BATCH, and a slow rank stepping at 6-10s
            # must flag as a STRAGGLER, not flap absent/present (which
            # would also keep resetting its straggler window).  Jobs
            # with faster liveness needs set MX_FLEET_STALE explicitly.
            raw = get_env("MX_FLEET_STALE", "")
            try:
                stale_after = float(raw) if raw not in (None, "") else \
                    max(2.0 * self.interval, 30.0)
            except (TypeError, ValueError):
                stale_after = max(2.0 * self.interval, 30.0)
        self.stale_after = float(stale_after)
        self.scrape_timeout = float(scrape_timeout)
        self.logger = logger or logging
        self._lock = threading.Lock()
        self._members: Dict[str, FleetMember] = {}
        self._state: Dict[str, _MemberState] = {}
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._scrapes = 0
        self._flagged: set = set()      # stragglers already warned about
        self.stragglers = StragglerDetector(factor=straggler_factor,
                                            window=window)
        self.slo = SLOTracker(window=window, targets=slo_targets)
        self._slo_phases = [p.strip() for p in str(
            get_env("MX_FLEET_SLO_PHASES", "queue_wait,serve_dispatch")
            or "").split(",") if p.strip()]
        self._prev_rates: Optional[Tuple[float, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # loop-ownership generation: stop() bumps it so a scrape loop
        # whose join timed out (stuck in socket IO) retires itself on
        # its next iteration instead of racing a restarted loop
        self._run_gen = 0
        self._wire_server = None
        self._http_server = None
        reg = _telemetry.registry
        self._g_members = reg.gauge(
            "fleet.members", doc="members present at the last scrape")
        self._g_absent = reg.gauge(
            "fleet.members_absent",
            doc="members that failed their last scrape (dead, "
                "unreachable, or heartbeat gone stale)")
        self._g_stragglers = reg.gauge(
            "fleet.stragglers",
            doc="workers currently over the straggler threshold "
                "(MX_FLEET_STRAGGLER_FACTOR x fleet median step time)")
        self._c_scrapes = reg.counter(
            "fleet.scrapes", doc="completed fleet scrape rounds")
        self._c_malformed = reg.counter(
            "fleet.malformed_beats",
            doc="heartbeat JSON payload lines that failed to parse "
                "(tolerated and counted; the beat itself still counts "
                "for liveness)")
        for m in members:
            self.add_member(m)

    # -- membership ---------------------------------------------------------
    def add_member(self, member: FleetMember) -> FleetMember:
        with self._lock:
            self._members[member.key] = member
            self._state.setdefault(member.key, _MemberState())
        return member

    def remove_member(self, key: str) -> None:
        with self._lock:
            self._members.pop(key, None)
            self._state.pop(key, None)

    def retire(self, key: str) -> None:
        """Elastic departure (ISSUE 16): a member that sent LEAVE (or
        was evicted by the kvstore membership table, or shrunk away by
        the supervisor) is retired from presence AND detector state in
        one step — unlike :meth:`remove_member` alone, this also clears
        its straggler window and any outstanding flag, so a voluntary
        shrink never false-alarms as a straggler/ABSENT member aging
        out over the miss-window.  All under _lock — the scrape thread
        mutates the same detector state mid-round."""
        with self._lock:
            self._members.pop(key, None)
            self._state.pop(key, None)
            self.stragglers.retire(key)
            self._flagged.discard(key)

    def members(self) -> List[FleetMember]:
        with self._lock:
            return list(self._members.values())

    # -- scraping -----------------------------------------------------------
    def _scrape_member(self, member: FleetMember):
        """(snapshot, source, age, malformed) or raises on failure."""
        if member.addr:
            snap = fetch_metrics(member.addr, fmt="json",
                                 timeout=self.scrape_timeout)
            return snap, "wire", None, 0
        return self._scrape_heartbeat(member)

    def _scrape_heartbeat(self, member: FleetMember):
        """Degraded fallback: the worker's liveness file.  Line 1 is
        the classic beat, line 2 the flight recorder's latest step
        record (telemetry.heartbeat_payload JSON).  A malformed JSON
        line is tolerated-and-counted — the beat still proves liveness.
        Synthesized into a minimal registry-shaped snapshot so one
        merge path serves both sources."""
        st = os.stat(member.heartbeat)
        # reading the liveness file IS this scrape path's job (the
        # degraded fallback for members with no wire face); it runs on
        # the collector's own thread at MX_FLEET_INTERVAL, never on a
        # dispatch path
        with open(member.heartbeat) as f:  # mxlint: disable=host-sync-in-hot-path
            lines = f.read().splitlines()
        _head, payload, malformed = _telemetry.parse_heartbeat(lines)
        age = time.time() - st.st_mtime
        ts = payload.get("ts")
        if _fault.is_virtual() and isinstance(ts, (int, float)):
            # same-clock age: beats stamp fault.now(); comparing wall
            # mtime against a virtual supervisor clock would misfire
            age = max(0.0, _fault.now() - float(ts))
        if age > self.stale_after:
            raise MXNetError(
                "heartbeat %s stale for %.3gs (> %.3gs)"
                % (member.heartbeat, age, self.stale_after))
        snap: Dict[str, Any] = {}

        def gauge(name, value):
            snap[name] = {"type": "gauge", "name": name,
                          "value": float(value)}

        if isinstance(payload.get("step"), (int, float)):
            snap["worker.steps"] = {"type": "counter",
                                    "name": "worker.steps",
                                    "value": int(payload["step"])}
        for field in ("steps_per_sec", "throughput", "wire_bytes",
                      "dispatches", "retries", "nan_events", "epoch",
                      "batch"):
            if isinstance(payload.get(field), (int, float)):
                gauge("worker.%s" % field, payload[field])
        for pname, dur in (payload.get("phases") or {}).items():
            if isinstance(dur, (int, float)):
                key = "worker.phase_seconds{phase=%s}" % pname
                snap[key] = {"type": "gauge",
                             "name": "worker.phase_seconds",
                             "labels": {"phase": str(pname)},
                             "value": float(dur)}
        return snap, "heartbeat", age, malformed

    def scrape_once(self) -> Dict[str, Any]:
        """One scrape round: poll every member CONCURRENTLY (one dead
        host blocking a connect for scrape_timeout must not stall the
        whole round past the interval — the absent-within-one-scrape
        promise holds per member, not per fleet), then merge, run
        detectors, publish fleet gauges, append the merged snapshot to
        the ring.  Returns the merged snapshot (the FLEET verb's
        payload)."""
        _active[0] = self
        members = self.members()
        results: Dict[str, tuple] = {}
        res_lock = threading.Lock()

        def scrape_one(m):
            try:
                r = self._scrape_member(m)
            except (OSError, ValueError, MXNetError) as e:
                r = (None, None, None, str(e))
            with res_lock:
                results[m.key] = r

        threads = [threading.Thread(target=scrape_one, args=(m,),
                                    daemon=True,
                                    name="mx-fleet-scrape-%s" % m.key)
                   for m in members]
        for t in threads:
            t.start()
        deadline = _fault.Deadline(self.scrape_timeout + 1.0)
        for t in threads:
            t.join(timeout=max(0.05, deadline.remaining()))
        with res_lock:
            for m in members:
                # a scraper thread still stuck past the budget counts
                # as an absent member this round; its late result is
                # simply dropped (next round scrapes fresh)
                results.setdefault(m.key,
                                   (None, None, None, "scrape timed out"))
            snap_results = dict(results)
        merged = self._fold(members, snap_results)
        self._publish(merged)
        return merged

    def _fold(self, members, results) -> Dict[str, Any]:
        """Fold scrape results into member state + the merged snapshot
        (under the lock; no IO, no instrument updates)."""
        now_ts = _fault.now()
        malformed_total = 0
        with self._lock:
            self._scrapes += 1
            member_meta: Dict[str, Dict[str, Any]] = {}
            mergeable: Dict[str, Dict[str, Any]] = {}
            counter_totals: Dict[str, Dict[str, Any]] = {}
            lat_delta: Dict[str, int] = {}
            worker_stats: Dict[str, Dict[str, Any]] = {}
            for m in members:
                st = self._state.setdefault(m.key, _MemberState())
                snap, source, age, info = results.get(
                    m.key, (None, None, None, "not scraped"))
                if snap is None:
                    st.present = False
                    st.absent_scrapes += 1
                    st.age = None
                else:
                    was_restart = self._rebase_counters(st, snap)
                    lat_delta = merge_bucket_maps(
                        [lat_delta,
                         self._hist_delta(st, snap, was_restart)],
                        name="slo_latency_window")
                    st.present = True
                    st.absent_scrapes = 0
                    st.source = source
                    st.age = age
                    st.last_snap = snap
                    if isinstance(info, int) and info:
                        # malformed heartbeat JSON line: tolerated (the
                        # beat still proves liveness), but counted
                        st.malformed += info
                        malformed_total += info
                    st.model = m.model or self._model_of(snap) or st.model
                    if m.role == "worker":
                        worker_stats[m.key] = self._worker_stat(snap)
                # counters keep advancing monotonically from the last
                # known (rebased) values even while a member is absent
                for key, raw in st.counter_raw.items():
                    base = st.counter_base.get(key, 0)
                    slot = counter_totals.setdefault(
                        key, {"total": 0, "per_member": {}})
                    slot["per_member"][m.key] = base + raw
                    slot["total"] += base + raw
                if st.present:
                    mergeable[m.key] = st.last_snap
                member_meta[m.key] = {
                    "role": m.role, "rank": m.rank,
                    "present": st.present,
                    "absent_scrapes": st.absent_scrapes,
                    # schema 2: the scrape address rides the row so a
                    # router can map per-member signals -> replica addr
                    "addr": m.addr,
                    "source": st.source, "model": st.model,
                    "age": round(st.age, 3) if st.age is not None
                    else None,
                    "error": None if st.present else
                    (info if isinstance(info, str) else "scrape failed"),
                }
            # counters come from the rebased running totals, not the
            # raw present-member values (restart discontinuities and
            # absent members are already folded in) — so the pure merge
            # skips its counter pass entirely
            base_merge = merge_snapshots(mergeable,
                                         include_counters=False)
            base_merge["counters"] = counter_totals
            # per-model rollup (ISSUE 20, schema 3): every model-labeled
            # serve.* counter folds into a {model: {name: total}} map —
            # the multi-model replica's per-model traffic, fleet-wide
            model_rollup: Dict[str, Dict[str, Any]] = {}
            for key, slot in counter_totals.items():
                if "{" not in key or not key.startswith("serve."):
                    continue
                name, rest = key.split("{", 1)
                mdl = None
                for part in rest.rstrip("}").split(","):
                    if part.startswith("model="):
                        mdl = part[len("model="):]
                if mdl is not None:
                    model_rollup.setdefault(mdl, {})[name] = \
                        slot["total"]
            straggler_findings = self.stragglers.update(worker_stats)
            rejected_d, offered_d = self._rate_deltas(counter_totals)
            queue_depth = self._queue_depth(base_merge["gauges"])
            slo = self.slo.update(lat_delta, rejected_d, offered_d,
                                  queue_depth)
            merged = {
                "schema": SCHEMA,
                "ts": now_ts,
                "wall_time": time.time(),
                "scrape": self._scrapes,
                "interval": self.interval,
                "members": member_meta,
                "counters": base_merge["counters"],
                "gauges": base_merge["gauges"],
                "histograms": base_merge["histograms"],
                "models": model_rollup,
                "stragglers": straggler_findings,
                "slo": slo,
                "malformed_beats": malformed_total,
            }
            self._ring.append(merged)
        return merged

    @staticmethod
    def _model_of(snap) -> Optional[str]:
        """The replica's live model from its serve.active_version
        gauges.  After a hot-swap to a differently-named servable the
        OLD model's gauge persists in the registry — versions are
        monotonic across swaps (ModelHost enforces it), so the gauge
        with the HIGHEST version is the live one."""
        best_v, best_model = None, None
        for entry in snap.values():
            if isinstance(entry, dict) and \
                    entry.get("name") == "serve.active_version":
                v = entry.get("value", 0) or 0
                if best_v is None or v > best_v:
                    best_v = v
                    best_model = (entry.get("labels") or {}).get("model")
        return best_model

    @staticmethod
    def _worker_stat(snap) -> Dict[str, Any]:
        phases = {}
        for entry in snap.values():
            if isinstance(entry, dict) and \
                    entry.get("name") == "worker.phase_seconds":
                pname = (entry.get("labels") or {}).get("phase")
                if pname:
                    phases[pname] = entry.get("value", 0.0)
        sps = (snap.get("worker.steps_per_sec") or {}).get("value")
        if sps and sps > 0:
            dur = 1.0 / float(sps)
        elif phases:
            dur = sum(phases.values())
        else:
            dur = None
        return {"step_seconds": dur, "phases": phases}

    def _rebase_counters(self, st: _MemberState, snap) -> bool:
        """Track counter values per member; a raw value BELOW the last
        seen one means the member restarted (process counters reset):
        the previous total folds into the base so the fleet total never
        moves backwards and never double-counts.  Returns whether a
        restart discontinuity was detected."""
        restarted = False
        for key, entry in snap.items():
            if not isinstance(entry, dict) or \
                    entry.get("type") != "counter":
                continue
            raw = entry.get("value", 0) or 0
            last = st.counter_raw.get(key)
            if last is not None and raw < last:
                st.counter_base[key] = \
                    st.counter_base.get(key, 0) + last
                restarted = True
            st.counter_raw[key] = raw
        return restarted

    def _hist_delta(self, st: _MemberState, snap,
                    was_restart: bool) -> Dict[str, int]:
        """This member's latency-histogram bucket delta since its last
        scrape, summed over the configured SLO phases.  On a restart
        the member's cumulative counts reset — the fresh counts ARE the
        delta (clamping at zero would silently drop them)."""
        delta: Dict[str, int] = {}
        for pname in self._slo_phases:
            key = "step_phase_seconds{phase=%s}" % pname
            entry = snap.get(key)
            if not isinstance(entry, dict) or \
                    entry.get("type") != "histogram":
                continue
            cur = entry.get("buckets") or {}
            prev = st.prev_hists.get(key)
            if prev is None:
                # FIRST sight of this member: its lifetime history is
                # not "this round's work" — folding it in would let a
                # collector attached to a long-running fleet compute
                # burn over all history and falsely latch a breach
                d = {}
            elif was_restart or set(prev) != set(cur):
                # restart: the counts reset — the fresh counts ARE the
                # work since the restart
                d = dict(cur)
            else:
                d = {k: max(0, cur[k] - prev.get(k, 0)) for k in cur}
            st.prev_hists[key] = dict(cur)
            delta = merge_bucket_maps([delta, d],
                                      name="slo_latency_window")
        return delta

    def _rate_deltas(self, counter_totals) -> Tuple[float, float]:
        """This round's (rejected, offered) DELTAS from the rebased
        running totals — what the SLO tracker windows over.  Totals are
        monotone by construction (restart rebasing), so the deltas are
        never negative."""
        rej = (counter_totals.get("serve.rejected") or {}).get("total", 0)
        req = (counter_totals.get("serve.requests") or {}).get("total", 0)
        offered = rej + req
        prev = self._prev_rates
        self._prev_rates = (rej, offered)
        if prev is None:
            # first round: lifetime totals are not one round's work —
            # a collector attaching to a running fleet must not compute
            # a rejection "rate" over all history (false breach latch)
            return 0.0, 0.0
        return max(0.0, rej - prev[0]), max(0.0, offered - prev[1])

    def _queue_depth(self, gauges) -> float:
        entry = gauges.get("serve.queue_rows")
        return float(entry["mean"]) if entry else 0.0

    def _publish(self, merged) -> None:
        """Registry + log side effects, outside the collector lock."""
        meta = merged["members"]
        present = sum(1 for m in meta.values() if m["present"])
        self._g_members.set(present)
        self._g_absent.set(len(meta) - present)
        self._c_scrapes.inc()
        if merged.get("malformed_beats"):
            self._c_malformed.inc(merged["malformed_beats"])
        findings = merged["stragglers"]
        self._g_stragglers.set(len(findings))
        reg = _telemetry.registry
        for slo, b in (merged["slo"].get("burn") or {}).items():
            reg.gauge("fleet.slo_burn", doc="windowed SLO burn "
                      "(observed/target; >1 = out of budget)",
                      labels={"slo": slo}).set(b)
        breached = merged["slo"].get("breached") or {}
        for slo in self.slo.targets:
            # written BOTH ways so an operator's SLOTracker.reset()
            # actually clears the exported gauge on the next scrape —
            # a latch nothing can un-latch is a stuck alert
            reg.gauge("fleet.slo_breached", doc="latched SLO breach "
                      "(stays raised until SLOTracker.reset)",
                      labels={"slo": slo}).set(1 if slo in breached
                                               else 0)
        current = {f["member"] for f in findings}
        with self._lock:      # vs retire() clearing a flag mid-round
            flagged = set(self._flagged)
        for f in findings:
            if f["member"] in flagged:
                continue
            dom = ""
            if f.get("dominant_phase"):
                dom = "; dominant phase %s (%.0f%%)" % (
                    f["dominant_phase"], 100 * f["dominant_share"])
            self.logger.warning(
                "fleet: %s is a straggler: step %.3gs = %.3gx the "
                "fleet median %.3gs%s",
                f["member"], f["step_seconds"], f["ratio"],
                f["fleet_median_seconds"], dom)
            if _telemetry.enabled():
                _telemetry.flight_recorder.record(
                    steps=0, extra={"event": "fleet.straggler",
                                    **{k: f[k] for k in
                                       ("member", "ratio",
                                        "dominant_phase")}})
        with self._lock:
            self._flagged = current

    # -- faces --------------------------------------------------------------
    def snapshot(self) -> Optional[Dict[str, Any]]:
        """The last merged fleet snapshot (None before the first
        scrape) — what the FLEET verb returns."""
        with self._lock:
            return self._ring[-1] if self._ring else None

    def ring(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def to_prometheus(self) -> str:
        """Federation exposition: every member's instruments re-labeled
        ``role``/``rank`` (and ``model`` when known) + this process's
        own registry (the ``fleet.*`` rollups).  One scrape = the whole
        fleet."""
        with self._lock:
            members = dict(self._members)
            states = {k: (st.last_snap, st.model)
                      for k, st in self._state.items() if st.last_snap}
        lines: List[str] = []
        typed: set = set()
        for mid in sorted(states):
            snap, model = states[mid]
            m = members.get(mid)
            extra = {"role": m.role if m else "?",
                     "rank": m.rank if m else "?"}
            if model:
                extra["model"] = model
            for key in sorted(snap):
                entry = snap[key]
                if not isinstance(entry, dict) or "type" not in entry:
                    continue
                name = _entry_name(key, entry)
                pname = "mx_" + _telemetry._prom_name(name)
                labels = dict(entry.get("labels") or {})
                labels.update(extra)
                if pname not in typed:
                    typed.add(pname)
                    lines.append("# TYPE %s %s" % (pname, entry["type"]))
                if entry["type"] in ("counter", "gauge"):
                    lines.append("%s%s %s" % (
                        pname, _telemetry._prom_labels(labels),
                        entry.get("value", 0)))
                    continue
                for le, cum in (entry.get("buckets") or {}).items():
                    lines.append("%s_bucket%s %d" % (
                        pname,
                        _telemetry._prom_labels(labels,
                                                'le="%s"' % le), cum))
                lines.append("%s_sum%s %g" % (
                    pname, _telemetry._prom_labels(labels),
                    entry.get("sum", 0.0)))
                lines.append("%s_count%s %d" % (
                    pname, _telemetry._prom_labels(labels),
                    entry.get("count", 0)))
        return "\n".join(lines) + "\n" + _telemetry.registry.to_prometheus()

    # -- lifecycle ----------------------------------------------------------
    def start(self, port: Optional[int] = None,
              http_port: Optional[int] = None) -> "FleetCollector":
        """Start the background scrape thread (and, when configured,
        the FLEET wire server / federation HTTP endpoint)."""
        _active[0] = self
        if port is None:
            raw = get_env("MX_FLEET_PORT", "")
            port = int(raw) if raw not in (None, "") else None
        if http_port is None:
            raw = get_env("MX_FLEET_HTTP_PORT", "")
            http_port = int(raw) if raw not in (None, "") else None
        if self._thread is None:
            # a stop()ed collector is restartable: clear the event or
            # the fresh thread exits on its first wait (silently dead —
            # snapshot() frozen, FLEET serving stale data).  The new
            # loop takes a fresh generation; an old loop whose join
            # timed out sees the mismatch and retires instead of
            # double-scraping alongside this one.
            self._stop.clear()
            with self._lock:
                self._run_gen += 1
                gen = self._run_gen
            self._thread = threading.Thread(
                target=self._run, args=(gen,), daemon=True,
                name="mx-fleet-collector")
            self._thread.start()
        if port is not None and self._wire_server is None:
            self._wire_server = serve_fleet(self, port)
        if http_port is not None and self._http_server is None:
            self._http_server = _serve_federation(self, http_port)
        return self

    def _run(self, gen: int) -> None:
        while not self._stop.wait(self.interval):
            with self._lock:
                superseded = gen != self._run_gen
            if superseded:
                return      # a newer loop owns scraping now
            try:
                self.scrape_once()
            except Exception:
                # the collector observes the fleet; it must never take
                # the fleet (or the supervisor hosting it) down
                self.logger.warning("fleet: scrape round failed",
                                    exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        # orphan any loop that misses the event window (e.g. blocked in
        # a scrape while the join below times out): on its next
        # iteration the generation mismatch retires it
        with self._lock:
            self._run_gen += 1
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, self.interval + 1.0))
            self._thread = None
        for srv in (self._wire_server, self._http_server):
            if srv is not None:
                try:
                    srv.shutdown()
                    srv.server_close()
                except OSError:
                    pass
        self._wire_server = self._http_server = None
        if get_env("MX_TELEMETRY_TRACE", ""):
            # the scrape spans become their own row in the merged
            # chrome trace (tools/telemetry_dump.py)
            _telemetry.dump_trace(role="fleet")

    @property
    def bound_ports(self) -> Dict[str, Optional[int]]:
        return {
            "wire": self._wire_server.server_address[1]
            if self._wire_server else None,
            "http": self._http_server.server_address[1]
            if self._http_server else None,
        }


# ---------------------------------------------------------------------------
# the FLEET wire server + federation HTTP endpoint
# ---------------------------------------------------------------------------

def serve_fleet(collector: FleetCollector, port: int,
                ready_file: Optional[str] = None):
    """Serve the collector over the kvstore-style wire: FLEET returns
    the merged snapshot (JSN payload), METRICS the whole-fleet
    federation exposition (fmt='json': the collector process's own
    registry snapshot).  Returns the started ThreadingTCPServer; caller
    owns shutdown (FleetCollector.stop does it for embedded use)."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    msg = recv_msg(self.request, idle_block=True)
                except (ConnectionError, OSError, TimeoutError):
                    return
                if isinstance(msg, tuple) and msg and msg[0] == "SEQ":
                    msg = msg[3]    # idempotent verbs: envelope is noise
                cmd = msg[0] if isinstance(msg, tuple) and msg else msg
                if cmd == "FLEET":
                    reply = (True, encode_json(collector.snapshot()
                                               or {"schema": SCHEMA,
                                                   "members": {}}))
                elif cmd == "METRICS":
                    fmt = msg[1] if isinstance(msg, tuple) and \
                        len(msg) > 1 else "prometheus"
                    text = _telemetry.registry.to_json(indent=1) \
                        if fmt == "json" else collector.to_prometheus()
                    reply = (True, encode_text(text))
                else:
                    reply = (False, "unknown fleet command %r" % (cmd,))
                try:
                    send_msg(self.request, reply)
                except (ConnectionError, OSError):
                    return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Server(("0.0.0.0", int(port)), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mx-fleet-wire")
    t.start()
    if ready_file:
        with open(ready_file, "w") as f:
            f.write("%d" % srv.server_address[1])
    return srv


def _serve_federation(collector: FleetCollector, port: int):
    """Prometheus federation HTTP endpoint: GET /metrics = the whole
    fleet in one scrape; GET /fleet.json = the merged snapshot."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/fleet.json"):
                body = json.dumps(collector.snapshot() or {},
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif self.path.startswith("/metrics") or self.path == "/":
                body = collector.to_prometheus().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):     # stay off stderr
            pass

    srv = ThreadingHTTPServer(("0.0.0.0", int(port)), Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="mx-fleet-federation")
    t.start()
    return srv
