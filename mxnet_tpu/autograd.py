"""Imperative autograd: tape recording + reverse-mode backward.

Reference: python/mxnet/autograd.py (record, pause, backward, grad, Function),
src/imperative/imperative.cc (Imperative::RecordOp, Imperative::Backward),
src/nnvm/gradient.cc (Gradient pass).

TPU-native design (SURVEY.md §3.3 TPU mapping): each eagerly-invoked op is
recorded as a tape node carrying the backward closure obtained from
``jax.vjp`` over the op's pure JAX implementation — jax.vjp plays the role of
the per-op FGradient attribute and runs the forward exactly once.
``backward()`` walks the tape in reverse topological order accumulating
cotangents and writes leaf gradients into the arrays attached by
``attach_grad`` honoring grad_req ('write' | 'add' | 'null').  A hybridized
block records ONE node whose vjp is the jit-compiled backward of the whole
cached graph, so the training hot path is two XLA executables, not a Python
loop (SURVEY.md §7.2 item 1).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "ambient_is_train",
           "backward", "grad", "mark_variables", "Function", "VariableNode"]

_state = threading.local()

# Cross-thread mirror of which threads are currently recording/training.
# XLA host callbacks (jax.pure_callback — the Custom-op bridge) execute on
# runtime threads that never entered an autograd scope; ambient_is_train()
# lets them see "is any thread training right now" instead of a fresh
# thread-local default of False.
_ambient_lock = threading.Lock()
_recording_threads: set = set()
_training_threads: set = set()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.explicit = False  # this thread never entered a scope
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def _mirror(which: set, flag: bool) -> None:
    ident = threading.get_ident()
    with _ambient_lock:
        (which.add if flag else which.discard)(ident)


def set_recording(flag: bool) -> bool:
    st = _st()
    old = st.recording
    st.recording = bool(flag)
    st.explicit = True
    _mirror(_recording_threads, st.recording)
    return old


def set_training(flag: bool) -> bool:
    st = _st()
    old = st.training
    st.training = bool(flag)
    st.explicit = True
    _mirror(_training_threads, st.training)
    return old


def ambient_is_train() -> bool:
    """Per-call train flag for code running on a thread that may not own the
    autograd scope (XLA host-callback threads).  Falls back to "any thread is
    currently recording/training" — correct for the single-trainer process;
    a process training and predicting on two threads at once sees train=True
    on both callback paths (documented edge)."""
    st = _st()
    if st.explicit:
        return st.recording or st.training
    with _ambient_lock:
        return bool(_recording_threads or _training_threads)


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training

    def __enter__(self):
        st = _st()
        self._old = (st.recording, st.training)
        if self._rec is not None:
            set_recording(self._rec)
        if self._train is not None:
            set_training(self._train)
        return self

    def __exit__(self, *exc):
        set_recording(self._old[0])
        set_training(self._old[1])
        return False


def record(train_mode: bool = True) -> _Scope:
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------


class VariableNode:
    """Leaf marker created by NDArray.attach_grad / mark_variables."""
    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array


class OpNode:
    """One recorded op: vjp closure + parent links (≈ nnvm::Node + AGInfo)."""
    __slots__ = ("name", "vjp_fn", "parents", "n_outputs", "rng_offset",
                 "primal_fn", "primal_vals", "primal_refs",
                 "out_structure", "out_avals")

    def __init__(self, name, vjp_fn, parents, n_outputs, rng_offset,
                 out_structure, out_avals, primal_fn=None, primal_vals=None,
                 primal_refs=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.parents = parents      # per-jax-input: VariableNode|OpNode|None
        self.n_outputs = n_outputs
        self.rng_offset = rng_offset
        self.out_structure = out_structure  # 'one' | 'tuple'
        self.out_avals = out_avals  # [(shape, dtype)] for zero-cotangent fill
        # higher-order support: re-linearization needs the op's pure fn and
        # its primal inputs (the stored vjp closure alone cannot yield
        # d(grad)/d(primal))
        self.primal_fn = primal_fn
        self.primal_vals = primal_vals   # list[jax.Array]
        self.primal_refs = primal_refs   # list[NDArray|None] (tape links)


def record_op(op, params: Dict[str, Any], nd_inputs, jax_in, ctx):
    """Called by ndarray.invoke while recording.  Runs forward via jax.vjp and
    wraps outputs with tape pointers."""
    from .ndarray.ndarray import NDArray

    def pure(*xs):
        return op.fn(*xs, **params)

    outs, vjp_fn = jax.vjp(pure, *jax_in)
    structure = "tuple" if isinstance(outs, tuple) else "one"
    outs_t = outs if structure == "tuple" else (outs,)
    rng_offset = 1 if op.needs_rng else 0

    parents: List[Any] = [None] * rng_offset
    for x in nd_inputs:
        if isinstance(x, NDArray):
            parents.append(x._ag_node)
        else:
            parents.append(None)
    avals = [(o.shape, o.dtype) for o in outs_t]
    refs = [None] * rng_offset + [x if isinstance(x, NDArray) else None
                                  for x in nd_inputs]
    node = OpNode(op.name, vjp_fn, parents, len(outs_t), rng_offset, structure,
                  avals, primal_fn=pure, primal_vals=list(jax_in),
                  primal_refs=refs)
    wrapped = []
    for i, o in enumerate(outs_t):
        nd = NDArray(o, ctx=ctx)
        nd._ag_node = (node, i)
        wrapped.append(nd)
    if structure == "one":
        return wrapped[0]
    return wrapped


def record_custom(vjp_fn, nd_inputs, outs, ctx, name="custom",
                  primal_fn=None):
    """Record a single node with a user/jit-supplied vjp (the CachedOp path).
    Pass primal_fn (pure over the nd_inputs' jax values) to keep the node
    differentiable under create_graph."""
    from .ndarray.ndarray import NDArray
    structure = "tuple" if isinstance(outs, tuple) else "one"
    outs_t = outs if structure == "tuple" else (outs,)
    parents = []
    for x in nd_inputs:
        parents.append(x._ag_node if isinstance(x, NDArray) else None)
    avals = [(o.shape, o.dtype) for o in outs_t]
    pvals = prefs = None
    if primal_fn is not None:
        pvals = [x._jax if isinstance(x, NDArray) else jnp.asarray(x)
                 for x in nd_inputs]
        prefs = [x if isinstance(x, NDArray) else None for x in nd_inputs]
    node = OpNode(name, vjp_fn, parents, len(outs_t), 0, structure, avals,
                  primal_fn=primal_fn, primal_vals=pvals, primal_refs=prefs)
    wrapped = []
    for i, o in enumerate(outs_t):
        nd = NDArray(o, ctx=ctx)
        nd._ag_node = (node, i)
        wrapped.append(nd)
    return wrapped[0] if structure == "one" else wrapped


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Reference: autograd.mark_variables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._ag_node = VariableNode(v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _topo_from(heads: Sequence[Tuple[OpNode, int]]) -> List[OpNode]:
    """Iterative post-order DFS from the head nodes (no recursion limit on
    deep tapes).  Post-order emits a node after all its producers, so the
    caller iterates ``reversed(order)`` to run heads-first backward."""
    seen = set()
    order: List[OpNode] = []
    for head, _ in heads:
        if not isinstance(head, OpNode) or id(head) in seen:
            continue
        seen.add(id(head))
        stack = [(head, iter(head.parents))]
        while stack:
            n, it = stack[-1]
            advanced = False
            for p in it:
                pn = p[0] if isinstance(p, tuple) else p
                if isinstance(pn, OpNode) and id(pn) not in seen:
                    seen.add(id(pn))
                    stack.append((pn, iter(pn.parents)))
                    advanced = True
                    break
            if not advanced:
                order.append(n)
                stack.pop()
    return order


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True) -> None:
    """Compute gradients of heads w.r.t. attached variables (writes .grad)."""
    from . import telemetry as _telemetry
    # step-phase span (ISSUE 8): eager Gluon loops get their backward
    # attributed; dispatch-time only (the tape replay enqueues async
    # XLA work, nothing here syncs it)
    with _telemetry.phase("backward"):
        _run_backward(heads, head_grads, retain_graph, write_leaves=True)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph: bool = False, train_mode: bool = True):
    """Reference: autograd.grad — returns grads instead of writing .grad.

    With ``create_graph=True`` the backward pass itself is RECORDED: every
    tape node's vjp closure is a jax-transformable function, so its
    application becomes a new tape node (via jax.vjp over the vjp),
    making the returned gradients differentiable — grad-of-grad composes
    to any order (reference: Imperative::Backward's create_graph)."""
    from .ndarray.ndarray import NDArray
    variables = list(variables)
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        # the backward computation itself must RECORD (cotangent fan-in
        # accumulation and dtype promotes are ordinary NDArray ops) — force
        # recording even when grad() is called outside a record() scope
        with _Scope(True, train_mode):
            got = _run_backward(heads, head_grads, retain_graph,
                                write_leaves=False, wanted=variables,
                                record_graph=True)
    else:
        got = _run_backward(heads, head_grads, retain_graph,
                            write_leaves=False, wanted=variables)
    out = []
    for v in variables:
        g = got.get(id(v))
        if g is None:
            raise MXNetError("one of the variables does not require gradient "
                             "or is unreachable from heads")
        out.append(g if isinstance(g, NDArray) else NDArray(g, ctx=v.context))
    return out


def _run_backward(heads, head_grads, retain_graph, write_leaves=True,
                  wanted=None, record_graph=False):
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is not None and isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent store: id(OpNode) -> list per output slot.  Values are raw
    # jax arrays normally; with record_graph they are NDArrays carrying
    # tape pointers so the backward computation is itself differentiable
    # (NDArray.__add__ in the accumulation below records too).
    cts: Dict[int, List[Optional[Any]]] = {}
    leaf_vals: Dict[int, Any] = {}
    leaf_refs: Dict[int, Any] = {}
    head_nodes: List[Tuple[OpNode, int]] = []

    def add_ct(target, value):
        if target is None:
            return
        if isinstance(target, VariableNode):
            arr = target.array
            prev = leaf_vals.get(id(arr))
            leaf_vals[id(arr)] = value if prev is None else prev + value
            leaf_refs[id(arr)] = arr
            return
        node, idx = target
        slot = cts.setdefault(id(node), [None] * node.n_outputs)
        slot[idx] = value if slot[idx] is None else slot[idx] + value

    for i, h in enumerate(heads):
        if h._ag_node is None:
            raise MXNetError("cannot differentiate a head that was not "
                             "computed while autograd was recording")
        hg = None
        if head_grads is not None and head_grads[i] is not None:
            hg = head_grads[i] if record_graph and \
                isinstance(head_grads[i], NDArray) else (
                head_grads[i]._jax if isinstance(head_grads[i], NDArray)
                else jnp.asarray(head_grads[i]))
        else:
            hg = jnp.ones(h.shape, h.dtype)
            if record_graph:
                hg = NDArray(hg)
        add_ct(h._ag_node, hg)
        if isinstance(h._ag_node, tuple):
            head_nodes.append(h._ag_node)

    order = _topo_from(head_nodes)

    # Incremental leaf finalization (ISSUE 5 overlap scheduling): a leaf's
    # gradient is FINAL the moment every tape node that consumes it has
    # been processed.  Writing it (and firing the grad buffer's overlap
    # hook) right then — instead of after the whole walk — lets a consumer
    # (Trainer fusion-bucket exchange) launch its collective while the
    # rest of backward is still running.  Backward visits heads first, so
    # late-layer leaves finalize earliest — which is exactly the order the
    # reverse-packed buckets close in.
    leaf_edges: Dict[int, int] = {}
    finalized: set = set()
    if write_leaves:
        for node in order:
            for p in node.parents:
                if isinstance(p, VariableNode):
                    k = id(p.array)
                    leaf_edges[k] = leaf_edges.get(k, 0) + 1

    def _write_leaf(arr, val):
        req = arr._grad_req
        if req == "null" or arr._grad is None:
            return
        if req == "add":
            arr._grad._set_jax(arr._grad._jax + val.astype(arr._grad.dtype))
        else:
            arr._grad._set_jax(val.astype(arr._grad.dtype))
            hook = getattr(arr._grad, "_grad_hook", None)
            if hook is not None:
                # 'write' only: an accumulating grad ('add') is not final
                # until the caller says so — overlap consumers drain it at
                # step time instead.  The hook runs on WHATEVER thread is
                # executing this backward (incl. XLA host-callback
                # threads), so hook targets may only touch state guarded
                # for cross-thread access — mxlint's concurrency pass
                # models every `._grad_hook = ...` target as a thread
                # root and enforces exactly that
                hook()

    def _note_consumed(node):
        for p in node.parents:
            if not isinstance(p, VariableNode):
                continue
            k = id(p.array)
            leaf_edges[k] -= 1
            if leaf_edges[k] == 0 and k not in finalized:
                finalized.add(k)
                if k in leaf_vals:
                    _write_leaf(leaf_refs[k], leaf_vals[k])

    # order: producers-before-consumers removed by reversal → walk heads first
    for node in reversed(order):
        slot = cts.get(id(node))
        if slot is None:
            # unreached node (pruned branch): its leaf inputs still count
            # this visit, or they would never finalize
            if write_leaves:
                _note_consumed(node)
            continue
        # Cotangents must match each output's dtype; a consumer may have
        # promoted (e.g. the AMP fp32-list casts a bf16 activation up before
        # a loss op), in which case its cotangent arrives wide — cast it
        # back, which is precisely the vjp of the implicit promote.
        cotangents = [
            jnp.zeros(node.out_avals[i][0], node.out_avals[i][1])
            if c is None
            else (c.astype(node.out_avals[i][1])
                  if c.dtype != node.out_avals[i][1] else c)
            for i, c in enumerate(slot)]
        if node.vjp_fn is None:
            raise MXNetError(
                "backward through op %r a second time, but the graph was "
                "freed; pass retain_graph=True to the first backward"
                % node.name)
        if record_graph:
            # higher-order: re-linearize the op from its PURE fn + primals
            # so the backward step is a fresh tape node differentiable in
            # BOTH the incoming cotangent and the primal inputs (the
            # stored vjp closure hides the primal dependency)
            if node.primal_fn is None:
                raise MXNetError(
                    "create_graph through %r: this node (hybridized block /"
                    " custom Function) does not retain its primal function;"
                    " higher-order autograd needs eagerly-recorded ops"
                    % node.name)
            ct_nds = [c if isinstance(c, NDArray) else NDArray(c)
                      for c in cotangents]
            jax_cts = [c._jax for c in ct_nds]
            n_ct = len(jax_cts)
            is_tuple = node.out_structure == "tuple"
            pure = node.primal_fn
            # only expose grads whose parent exists: a dangling grad (e.g.
            # x^y's dy = x^y·ln x at negative x) can be NaN, and even a
            # zero cotangent would propagate 0*NaN through the next vjp
            keep = [i for i, p in enumerate(node.parents) if p is not None]

            def apply(*args, pure=pure, n_ct=n_ct, is_tuple=is_tuple,
                      keep=tuple(keep)):
                cs, prims = args[:n_ct], args[n_ct:]
                _, vjp = jax.vjp(pure, *prims)
                gr = vjp(tuple(cs) if is_tuple else cs[0])
                return tuple(gr[i] for i in keep)

            outs, vjp2 = jax.vjp(apply, *jax_cts, *node.primal_vals)
            rec_inputs = list(ct_nds) + [
                r if r is not None else v
                for r, v in zip(node.primal_refs, node.primal_vals)]
            # primal_fn threads through so grad-of-grad-of-grad composes
            wrapped = record_custom(vjp2, rec_inputs, tuple(outs), None,
                                    name=node.name + "_backward",
                                    primal_fn=apply)
            kept_nd = wrapped if isinstance(wrapped, (list, tuple)) \
                else [wrapped]
            grads = [None] * len(node.parents)
            for i, g in zip(keep, kept_nd):
                grads[i] = g
        else:
            ct_in = tuple(cotangents) if node.out_structure == "tuple" \
                else cotangents[0]
            grads = node.vjp_fn(ct_in)
        if not retain_graph:
            # free BOTH the vjp residuals and the higher-order primal refs
            # — otherwise every op input's device buffer stays pinned via
            # the tape after a plain backward
            node.vjp_fn = None
            node.primal_fn = None
            node.primal_vals = None
            node.primal_refs = None
        for parent, g in zip(node.parents, grads):
            if parent is None or g is None:
                continue
            gj = g._jax if hasattr(g, "_jax") else g
            if getattr(gj, "dtype", None) == jax.dtypes.float0:
                # jax.vjp's "no gradient" marker for integer inputs
                # (index operands of gather/clip/mod): nothing flows
                continue
            add_ct(parent, g)
        if write_leaves:
            # this node's contributions are in: any leaf it was the last
            # consumer of is now final — write it and fire its hook
            _note_consumed(node)

    if write_leaves:
        # sweep leaves the walk could not finalize (heads that ARE leaves,
        # zero-consumer edge cases)
        for key, val in leaf_vals.items():
            if key not in finalized:
                _write_leaf(leaf_refs[key], val)
        return None
    return dict(leaf_vals)


# ---------------------------------------------------------------------------
# custom differentiable Function (reference: autograd.Function)
# ---------------------------------------------------------------------------


class Function:
    """User-defined differentiable function.

    Subclass and implement forward(self, *inputs) and backward(self,
    *output_grads), both over NDArrays.  Mirrors python/mxnet/autograd.py
    (class Function).
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outs = self.forward(*inputs)
        single = not isinstance(outs, (list, tuple))
        outs_t = (outs,) if single else tuple(outs)
        if not is_recording():
            return outs
        func = self

        def vjp_fn(cotangents):
            cts = (cotangents,) if single else cotangents
            with pause():
                gr = func.backward(*[NDArray(c) for c in cts])
            if not isinstance(gr, (list, tuple)):
                gr = (gr,)
            return tuple(g._jax if isinstance(g, NDArray) else g for g in gr)

        ctx = inputs[0].context if inputs and isinstance(inputs[0], NDArray) \
            else None
        jax_outs = tuple(o._jax for o in outs_t)
        res = record_custom(vjp_fn, list(inputs),
                            jax_outs if not single else jax_outs[0],
                            ctx, name=type(self).__name__)
        return res


def get_symbol(x):
    """Reference: autograd.get_symbol — retrieve the recorded compute
    history of an NDArray as a Symbol.  This rebuild's tape records jax
    vjp closures, not named graph nodes, so the imperative history is
    not reconstructible as a Symbol; the supported route to a symbolic
    graph is HybridBlock.hybridize()+export (or SymbolBlock), which
    trace through the same kernels with full fidelity."""
    raise MXNetError(
        "autograd.get_symbol is not supported on the TPU rebuild: the "
        "autograd tape holds jax vjp closures, not graph nodes.  Use "
        "net.hybridize() + net.export(...) (or gluon.SymbolBlock) to "
        "obtain the symbolic graph of a computation.")
