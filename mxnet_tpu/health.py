"""In-process training health guards: NaN policy, step watchdog, heartbeat.

ISSUE 1 built the *recovery* primitives (retrying kvstore client,
crash-safe checkpoints, ``fit(checkpoint_dir=..., auto_resume)``); this
module supplies the *detection* half that makes them fire in practice.
The reference treated step- and process-level health as the scheduler's
problem (restart the container, resubmit the job); the TensorFlow
supervisor/monitored-session model (PAPERS.md arXiv:1605.08695) folds it
into the training stack instead, and that is the shape rebuilt here —
three small guards the fit loop installs and ``tools/launch.py``'s
process supervisor observes from outside:

* :class:`GradientGuard` — ``MX_NAN_POLICY`` (``warn`` | ``skip_batch``
  | ``raise``; empty disables).  Scans the step's gradients for NaN/Inf
  after backward, before update — ``skip_batch`` drops the poisoned
  update so the parameters stay finite, ``raise`` fails the rank fast
  (the supervisor then restarts it from the last checkpoint).  Same
  observable surface as :class:`~mxnet_tpu.monitor.Monitor` stat hooks
  (the bound gradient arrays), but cheap enough to run every batch.

* :class:`Watchdog` — ``MX_STEP_TIMEOUT``.  A daemon thread that, when
  the fit loop stops petting it for longer than the timeout, dumps every
  thread's stack to stderr and exits the process nonzero
  (:data:`WATCHDOG_EXIT_CODE`), converting a silent wedge — a deadlocked
  collective, a hung host callback — into a crash the supervisor can
  see and restart.  All timing goes through :mod:`mxnet_tpu.fault`'s
  module clock, so chaos tests drive expiry on a virtual clock with no
  real sleeps.

* :class:`Heartbeat` — ``MX_HEARTBEAT_FILE``.  Atomically rewrites a
  per-rank liveness file every batch; the supervisor reads its mtime to
  distinguish *slow* (file fresh, leave it alone) from *wedged* (file
  stale beyond ``--hang-timeout``, kill and restart) without any wire
  protocol between them.

:class:`StepGuard` bundles all three behind the four calls the fit loop
makes (``batch_start`` / ``allow_update`` / ``batch_end`` / ``close``);
``StepGuard.from_env()`` arms only what the environment asks for, so an
unconfigured process pays one no-op attribute check per batch.
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import traceback
from typing import Callable, Iterable, List, Optional, Tuple

from . import fault as _fault
from . import telemetry as _telemetry
from .base import MXNetError, get_env

__all__ = ["WATCHDOG_EXIT_CODE", "NAN_POLICIES", "nonfinite_grads",
           "dump_all_stacks", "GradientGuard", "Watchdog", "Heartbeat",
           "StepGuard"]

# Distinct from generic failure (1) and the injected-crash server exit
# (17) so the supervisor's logs say WHY a rank died; 86 stays clear of
# the shell's 126/127/128+n conventions.
WATCHDOG_EXIT_CODE = 86

NAN_POLICIES = ("", "warn", "skip_batch", "raise")


def nonfinite_grads(named_grads: Iterable[Tuple[str, object]]) -> List[str]:
    """Names of gradients containing NaN/Inf.  Accepts (name, NDArray)
    pairs (None gradients skipped — fixed params).

    The happy path costs ONE host sync: the per-array all-finite
    reductions stay on device and collapse through a single fused
    ``jnp.all``; per-name blame (one sync per array) is computed only
    on the rare poisoned batch."""
    import jax.numpy as jnp
    named = [(n, getattr(g, "_jax", g)) for n, g in named_grads
             if g is not None]
    if not named:
        return []
    finite = [jnp.isfinite(a).all() for _n, a in named]
    if bool(jnp.all(jnp.stack(finite))):
        return []
    return [n for (n, _a), f in zip(named, finite) if not bool(f)]


def dump_all_stacks(file=None) -> None:
    """Write every live thread's stack to ``file`` (default stderr).

    Pure-Python (sys._current_frames + traceback) rather than
    faulthandler so the output can go to any text stream — tests capture
    it in a StringIO, the watchdog sends it to stderr where the
    supervisor's log collector finds it."""
    file = file if file is not None else sys.stderr
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        print("--- thread %s (%s) ---" % (ident, names.get(ident, "?")),
              file=file)
        for line in traceback.format_stack(frame):
            file.write(line)
    file.flush()


class GradientGuard:
    """Apply the ``MX_NAN_POLICY`` to one step's gradients.

    ``allow_update(named_grads)`` returns False when the update must be
    skipped; ``raise`` policy raises :class:`MXNetError` naming the
    offending arrays instead."""

    def __init__(self, policy: str = "", logger=None):
        if policy not in NAN_POLICIES:
            raise ValueError(
                "MX_NAN_POLICY must be one of %s, got %r"
                % ("|".join(p for p in NAN_POLICIES if p), policy))
        self.policy = policy
        self.logger = logger or logging
        self.nan_events = 0          # batches with any non-finite grad
        self.skipped_batches = 0     # updates dropped under skip_batch

    def allow_update(self, named_grads) -> bool:
        if not self.policy:
            return True
        bad = nonfinite_grads(named_grads)
        if not bad:
            return True
        self.nan_events += 1
        # registry counter (ISSUE 8): NaN-guard hits ride every flight-
        # recorder step record and the crash-dump counters snapshot
        _telemetry.registry.counter(
            "health.nan_events",
            doc="batches with non-finite gradients (MX_NAN_POLICY)").inc()
        shown = ", ".join(bad[:4]) + ("..." if len(bad) > 4 else "")
        if self.policy == "raise":
            # the raise kills this rank: leave the flight recorder's
            # last step records in MX_CRASH_DIR on the way out
            _telemetry.dump_crash(
                "nan_policy_raise: non-finite gradient(s) in %s" % shown)
            raise MXNetError(
                "non-finite gradient(s) in %s (MX_NAN_POLICY=raise)"
                % shown)
        if self.policy == "skip_batch":
            self.skipped_batches += 1
            self.logger.warning(
                "health: non-finite gradient(s) in %s - skipping this "
                "batch's update (MX_NAN_POLICY=skip_batch, %d skipped "
                "so far)", shown, self.skipped_batches)
            return False
        self.logger.warning(
            "health: non-finite gradient(s) in %s (MX_NAN_POLICY=warn: "
            "update applied anyway)", shown)
        return True


class Watchdog:
    """Hung-step watchdog: no ``pet()`` for > ``timeout`` seconds ⇒ dump
    all thread stacks and exit nonzero so the supervisor restarts the
    rank.

    Timing reads :func:`mxnet_tpu.fault.now` — under
    ``fault.use_virtual_time()`` tests drive ``expired()``/``check()``
    directly with zero real sleeps.  The background thread (``start()``)
    is production-only plumbing: it polls ``check()`` every ``poll``
    real seconds, so a hang is detected within ``timeout + poll`` —
    ``poll`` defaults to ``timeout / 2`` (bounded to [0.05, 1.0] s),
    keeping detection inside 2x the configured timeout."""

    def __init__(self, timeout: float,
                 on_timeout: Optional[Callable[[], None]] = None,
                 poll: Optional[float] = None, logger=None):
        self.timeout = float(timeout)
        if self.timeout <= 0:
            raise ValueError("Watchdog timeout must be > 0")
        self.poll = float(poll) if poll is not None else \
            min(1.0, max(0.05, self.timeout / 2.0))
        self.on_timeout = on_timeout
        self.logger = logger or logging
        # _last is petted by the fit loop (main thread) and read by the
        # watchdog thread every poll tick; the lock makes arm/disarm
        # atomic with the expiry read (a torn suspend()+pet() pair must
        # never be observed as armed-with-stale-stamp)
        self._lock = threading.Lock()
        self._last: Optional[float] = None   # None = not yet armed
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired = False

    def pet(self) -> None:
        """Mark progress: the current step window restarts now."""
        with self._lock:
            self._last = _fault.now()

    def suspend(self) -> None:
        """Disarm until the next pet() (long known-slow phases: eval,
        checkpoint restore)."""
        with self._lock:
            self._last = None

    def expired(self) -> bool:
        with self._lock:
            last = self._last
        return last is not None and (_fault.now() - last) > self.timeout

    def check(self) -> bool:
        """One poll tick: fire on expiry.  Returns True when fired."""
        if self.fired or not self.expired():
            return False
        self.fired = True
        self._fire()
        return True

    def _fire(self) -> None:
        sys.stderr.write(
            "watchdog: no training-step progress for > %.3gs "
            "(MX_STEP_TIMEOUT) - dumping thread stacks and exiting %d\n"
            % (self.timeout, WATCHDOG_EXIT_CODE))
        # flight-recorder crash dump FIRST (ISSUE 8): the ring's last
        # step records say what the rank was doing when it wedged —
        # written before the stack dump so even a hung stderr cannot
        # lose it
        _telemetry.dump_crash(
            "watchdog: no step progress for > %.3gs (MX_STEP_TIMEOUT)"
            % self.timeout)
        dump_all_stacks(sys.stderr)
        if self.on_timeout is not None:
            self.on_timeout()
            return
        os._exit(WATCHDOG_EXIT_CODE)

    # -- background thread (production path) --------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="mx-step-watchdog", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            if self.check():
                return

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class Heartbeat:
    """Per-rank liveness file: ``beat()`` atomically rewrites it with
    ``<unix-time> <epoch> <batch>``; the supervisor reads the mtime."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def beat(self, epoch: int = 0, nbatch: int = 0) -> None:
        # telemetry payload (ISSUE 8): the latest flight-recorder step
        # record rides line 2 as compact JSON (step, throughput, last-
        # exchange bytes) — what the supervisor's fleet status table
        # renders without any wire protocol.  Line 1 keeps the classic
        # `<unix-time> <epoch> <batch>` format.
        self._write("%d %d" % (epoch, nbatch),
                    payload=_telemetry.heartbeat_payload())

    def done(self) -> None:
        """Final beat: training finished, the process may legitimately
        go silent now (export, final eval).  The supervisor sees the
        'done' token and stops hang enforcement for this rank."""
        self._write("done")

    def _write(self, tail: str, payload=None) -> None:
        import json as _json
        import time as _time
        tmp = "%s.tmp.%d" % (self.path, os.getpid())
        try:
            with open(tmp, "w") as f:
                # wall-clock ON PURPOSE: the beat's payload is a human-
                # readable timestamp; liveness uses the file's mtime
                f.write("%f %s\n" % (_time.time(), tail))  # mxlint: disable=wall-clock-in-fault-path
                if payload:
                    f.write(_json.dumps(payload,
                                        separators=(",", ":")) + "\n")
            os.replace(tmp, self.path)
        except (OSError, TypeError, ValueError):
            pass    # liveness is advisory - never fail training over it

    def remove(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass


class StepGuard:
    """The fit loop's composite guard: watchdog + heartbeat + NaN policy.

    All three are optional; :meth:`from_env` arms whichever the
    environment configures.  Usage (what ``BaseModule.fit`` does)::

        guard = StepGuard.from_env(logger=self.logger)
        try:
            for epoch ...:
                for nbatch, batch ...:
                    guard.batch_start()
                    forward_backward(batch)
                    if guard.allow_update(named_grads()):
                        update()
                    guard.batch_end(epoch, nbatch)
        finally:
            guard.close()
    """

    def __init__(self, nan_policy: str = "",
                 step_timeout: Optional[float] = None,
                 heartbeat_path: Optional[str] = None,
                 logger=None, on_timeout=None):
        self.logger = logger or logging
        self.grad_guard = GradientGuard(nan_policy, logger=self.logger) \
            if nan_policy else None
        self.watchdog = None
        if step_timeout:
            self.watchdog = Watchdog(step_timeout, logger=self.logger,
                                     on_timeout=on_timeout).start()
        self.heartbeat = Heartbeat(heartbeat_path) if heartbeat_path \
            else None
        self._steps = 0     # completed batches: arms the watchdog

    @classmethod
    def from_env(cls, logger=None, **overrides) -> "StepGuard":
        timeout = get_env("MX_STEP_TIMEOUT", dtype=float)
        kwargs = dict(
            nan_policy=get_env("MX_NAN_POLICY", "") or "",
            step_timeout=timeout if timeout and timeout > 0 else None,
            heartbeat_path=get_env("MX_HEARTBEAT_FILE", "") or None,
        )
        kwargs.update(overrides)
        return cls(logger=logger, **kwargs)

    @property
    def armed(self) -> bool:
        return (self.grad_guard is not None or self.watchdog is not None
                or self.heartbeat is not None)

    def batch_start(self) -> None:
        # the watchdog arms only once a batch has COMPLETED: the first
        # batch includes whole-graph jit compilation (and a restart's
        # re-compilation), which a steady-state MX_STEP_TIMEOUT must
        # not count as a hang — the same grace launch.py's heartbeat
        # liveness grants slow startup
        if self.watchdog is not None and self._steps > 0:
            self.watchdog.pet()

    def allow_update(self, named_grads) -> bool:
        if self.grad_guard is None:
            return True
        return self.grad_guard.allow_update(named_grads)

    def batch_end(self, epoch: int = 0, nbatch: int = 0) -> None:
        self._steps += 1
        # one flight-recorder step record per completed batch (ISSUE 8)
        # — BEFORE the heartbeat so the beat's JSON payload carries THIS
        # step, not the previous one
        _telemetry.note_step(epoch=epoch, batch=nbatch)
        if self.watchdog is not None:
            self.watchdog.pet()
        if self.heartbeat is not None:
            self.heartbeat.beat(epoch, nbatch)

    def epoch_end(self, epoch: int = 0) -> None:
        """Between epochs (checkpoint save, eval) steps legitimately
        stall - keep the heartbeat fresh and the watchdog disarmed."""
        if self.watchdog is not None:
            self.watchdog.suspend()
        if self.heartbeat is not None:
            self.heartbeat.beat(epoch, -1)

    @property
    def skipped_batches(self) -> int:
        return self.grad_guard.skipped_batches if self.grad_guard else 0

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.heartbeat is not None:
            self.heartbeat.done()   # post-fit silence is not a wedge
