"""ONNX export/import (reference: python/mxnet/onnx/mx2onnx —
export_model — and mx2onnx's onnx2mx import path).

This environment has no ``onnx`` package, so the ModelProto is written
and read DIRECTLY in protobuf wire format (varint + length-delimited
fields; the field numbers below are onnx.proto's).  The subset covers
the classic deploy graphs: Gemm/Conv/BatchNormalization/Pooling/
activations/elementwise/Concat/Reshape/Transpose/Flatten/Dropout/
Softmax — enough for the model-zoo CNN/MLP family.  Round-trip
(export → import → identical outputs) is pinned by tests; conformance
against onnxruntime needs a network-enabled environment.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]


# ---------------------------------------------------------------------------
# protobuf wire-format primitives
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _key(field, 0) + _varint(int(value))


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode("utf-8"))


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _scan(buf: bytes):
    """Yield (field, wire, value, start, end) messages."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        else:
            raise MXNetError("onnx: unsupported wire type %d" % wire)


# ---------------------------------------------------------------------------
# onnx.proto field numbers (ModelProto and friends)
# ---------------------------------------------------------------------------

_DT = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
       "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}
_DT_INV = {v: k for k, v in _DT.items()}


def _tensor(name: str, arr: _np.ndarray) -> bytes:
    dt = _DT[str(arr.dtype)]
    out = b"".join(_f_varint(1, d) for d in arr.shape)
    out += _f_varint(2, dt)
    out += _f_str(8, name)
    out += _f_bytes(9, _np.ascontiguousarray(arr).tobytes())
    return out


def _parse_tensor(buf: bytes) -> Tuple[str, _np.ndarray]:
    dims: List[int] = []
    dtype = 1
    name = ""
    raw = b""
    floats: List[float] = []
    for field, wire, val in _scan(buf):
        if field == 1 and wire == 0:
            dims.append(val)
        elif field == 2:
            dtype = val
        elif field == 8:
            name = val.decode("utf-8")
        elif field == 9:
            raw = val
        elif field == 4 and wire == 2:  # packed float_data
            floats = list(struct.unpack("<%df" % (len(val) // 4), val))
    np_dt = _np.dtype(_DT_INV.get(dtype, "float32"))
    if raw:
        arr = _np.frombuffer(raw, np_dt).reshape(dims).copy()
    else:
        arr = _np.asarray(floats, np_dt).reshape(dims)
    return name, arr


def _attr(name: str, value) -> bytes:
    out = _f_str(1, name)
    if isinstance(value, float):
        out += _key(2, 5) + struct.pack("<f", value) + _f_varint(20, 1)
    elif isinstance(value, bool) or isinstance(value, int):
        out += _f_varint(3, int(value)) + _f_varint(20, 2)
    elif isinstance(value, str):
        out += _f_bytes(4, value.encode()) + _f_varint(20, 3)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            out += b"".join(_key(7, 5) + struct.pack("<f", v)
                            for v in value)
            out += _f_varint(20, 6)
        else:
            out += b"".join(_f_varint(8, int(v)) for v in value)
            out += _f_varint(20, 7)
    else:
        raise MXNetError("onnx attr %r: unsupported %r" % (name, value))
    return out


def _signed64(v: int) -> int:
    """Protobuf int64 is two's-complement in a 64-bit varint."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _parse_attr(buf: bytes):
    name = ""
    fval = None
    ival = None
    sval = None
    floats: List[float] = []
    ints: List[int] = []
    atype = 0
    for field, wire, val in _scan(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:
            fval = struct.unpack("<f", val)[0]
        elif field == 3:
            ival = _signed64(val)
        elif field == 4:
            sval = val.decode("utf-8")
        elif field == 7:
            floats.append(struct.unpack("<f", val)[0])
        elif field == 8:
            ints.append(_signed64(val))
        elif field == 20:
            atype = val
    if atype == 1:
        return name, fval
    if atype == 2:
        return name, ival
    if atype == 3:
        return name, sval
    if atype == 6:
        return name, floats
    return name, ints


_ref_sink = None    # set by export_model: collects node input names


def _node(op_type: str, inputs: List[str], outputs: List[str], name: str,
          attrs: Dict[str, Any]) -> bytes:
    if _ref_sink is not None:
        _ref_sink.update(inputs)
    out = b"".join(_f_str(1, i) for i in inputs)
    out += b"".join(_f_str(2, o) for o in outputs)
    out += _f_str(3, name) + _f_str(4, op_type)
    out += b"".join(_f_bytes(5, _attr(k, v)) for k, v in attrs.items())
    return out


def _parse_node(buf: bytes):
    inputs: List[str] = []
    outputs: List[str] = []
    name = op_type = ""
    attrs: Dict[str, Any] = {}
    for field, wire, val in _scan(buf):
        if field == 1:
            inputs.append(val.decode("utf-8"))
        elif field == 2:
            outputs.append(val.decode("utf-8"))
        elif field == 3:
            name = val.decode("utf-8")
        elif field == 4:
            op_type = val.decode("utf-8")
        elif field == 5:
            k, v = _parse_attr(val)
            attrs[k] = v
    return op_type, inputs, outputs, name, attrs


def _value_info(name: str, shape: Tuple[int, ...], elem_type: int = 1) \
        -> bytes:
    shape_pb = b"".join(
        _f_bytes(1, _f_varint(1, d)) for d in shape)        # Dimension
    tensor_pb = _f_varint(1, elem_type) + _f_bytes(2, shape_pb)
    type_pb = _f_bytes(1, tensor_pb)                        # tensor_type
    return _f_str(1, name) + _f_bytes(2, type_pb)


def _parse_value_info(buf: bytes):
    name = ""
    shape: List[int] = []
    for field, wire, val in _scan(buf):
        if field == 1:
            name = val.decode("utf-8")
        elif field == 2:
            for f2, w2, v2 in _scan(val):
                if f2 == 1:                                  # tensor_type
                    for f3, w3, v3 in _scan(v2):
                        if f3 == 2:                          # shape
                            for f4, w4, v4 in _scan(v3):
                                if f4 == 1:                  # dim
                                    for f5, w5, v5 in _scan(v4):
                                        if f5 == 1:
                                            shape.append(v5)
    return name, tuple(shape)


# ---------------------------------------------------------------------------
# mx symbol -> onnx graph
# ---------------------------------------------------------------------------


def _walk(symbol):
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for child, _ in node.inputs:
            visit(child)
        order.append(node)
    for node, _ in symbol._heads:
        visit(node)
    return order


def _a(attrs, key, default=None):
    import ast
    v = attrs.get(key, default)
    if isinstance(v, str):
        try:
            return ast.literal_eval(v)
        except (ValueError, SyntaxError):
            return v
    return v


def _conv_attrs(attrs):
    kernel = tuple(_a(attrs, "kernel"))
    stride = tuple(_a(attrs, "stride", (1,) * len(kernel)) or
                   (1,) * len(kernel))
    pad = tuple(_a(attrs, "pad", (0,) * len(kernel)) or (0,) * len(kernel))
    dilate = tuple(_a(attrs, "dilate", (1,) * len(kernel)) or
                   (1,) * len(kernel))
    return {"kernel_shape": list(kernel), "strides": list(stride),
            "pads": list(pad) * 2, "dilations": list(dilate),
            "group": int(_a(attrs, "num_group", 1) or 1)}


def export_model(sym, params, input_shapes=None, input_types=_np.float32,
                 onnx_file_path="model.onnx", opset_version=13,
                 verbose=False, **kw):
    """Reference: mx.onnx.export_model(sym, params, in_shapes, in_types,
    onnx_file_path).  `sym` may be a Symbol or a symbol.json path; `params`
    a dict (NDArray values) or a .params path."""
    from .. import ndarray as nd
    from ..symbol import Symbol, load as sym_load

    if isinstance(sym, str):
        sym = sym_load(sym)
    if isinstance(params, str):
        loaded = nd.load(params)
        params = {k.split(":", 1)[-1]: v for k, v in loaded.items()}
    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else _np.asarray(v))
              for k, v in (params or {}).items()}

    nodes_pb: List[bytes] = []
    inits_pb: List[bytes] = []
    inputs_pb: List[bytes] = []
    outputs_pb: List[bytes] = []
    consumed_only_transposed: set = set()
    param_nodes: List[str] = []
    direct_refs: set = set()    # filled by _node as nodes are emitted
    global _ref_sink
    _ref_sink = direct_refs

    arg_names = sym.list_arguments()
    data_names = [n for n in arg_names if n not in params]
    shapes = dict(zip(data_names, input_shapes or []))

    def out_name(node, idx=0):
        return node.name if idx == 0 else "%s_out%d" % (node.name, idx)

    try:
        for node in _walk(sym):
            op = node.op
            attrs = node.attrs or {}
            ins = [out_name(c, i) for c, i in node.inputs]
            if op == "null":
                if node.name in params:
                    param_nodes.append(node.name)
                else:
                    inputs_pb.append(_f_bytes(11, _value_info(
                        node.name, shapes.get(node.name, ()))))
                continue
            name = node.name
            outs = [out_name(node)]
            if op == "FullyConnected":
                no_bias = str(attrs.get("no_bias", "False")) in ("True", "1")
                flatten = str(attrs.get("flatten", "True")) not in ("False", "0")
                if flatten:
                    flat_in = ins[0] + "_flat"
                    nodes_pb.append(_f_bytes(1, _node(
                        "Flatten", [ins[0]], [flat_in], name + "_flatten",
                        {"axis": 1})))
                    gemm_in = [flat_in, ins[1]] + ([] if no_bias else [ins[2]])
                    nodes_pb.append(_f_bytes(1, _node(
                        "Gemm", gemm_in, outs, name,
                        {"alpha": 1.0, "beta": 1.0, "transA": 0, "transB": 1})))
                else:
                    # per-position projection over N-D input: ONNX Gemm is 2-D
                    # only, so emit MatMul against a TRANSPOSED weight
                    # initializer (+ Add for bias)
                    wname = ins[1]
                    if wname not in params:
                        raise MXNetError(
                            "onnx export: FullyConnected(flatten=False) needs "
                            "its weight as a parameter (got graph input %r)"
                            % wname)
                    wt_name = wname + "_T"
                    if wt_name not in params:
                        params[wt_name] = _np.ascontiguousarray(
                            params[wname].T)
                    consumed_only_transposed.add(wname)
                    mm_out = outs[0] if no_bias else name + "_mm"
                    nodes_pb.append(_f_bytes(1, _node(
                        "MatMul", [ins[0], wt_name], [mm_out],
                        name + "_matmul", {})))
                    if not no_bias:
                        nodes_pb.append(_f_bytes(1, _node(
                            "Add", [mm_out, ins[2]], outs, name, {})))
            elif op == "Convolution":
                no_bias = str(attrs.get("no_bias", "False")) in ("True", "1")
                conv_in = ins[:2] + ([] if no_bias else [ins[2]])
                nodes_pb.append(_f_bytes(1, _node("Conv", conv_in, outs, name,
                                                  _conv_attrs(attrs))))
            elif op == "Activation":
                act = attrs.get("act_type", "relu")
                onnx_op = {"relu": "Relu", "sigmoid": "Sigmoid",
                           "tanh": "Tanh", "softrelu": "Softplus"}.get(act)
                if onnx_op is None:
                    raise MXNetError("onnx export: Activation %r" % act)
                nodes_pb.append(_f_bytes(1, _node(onnx_op, ins, outs, name, {})))
            elif op == "BatchNorm":
                fix_gamma = str(attrs.get("fix_gamma", "True")) not in \
                    ("False", "0")
                if fix_gamma and ins[1] in params:
                    # mxnet treats gamma as all-ones under fix_gamma (the
                    # default); the exported graph must match that forward
                    params[ins[1]] = _np.ones_like(params[ins[1]])
                nodes_pb.append(_f_bytes(1, _node(
                    "BatchNormalization",
                    [ins[0], ins[1], ins[2], ins[3], ins[4]], outs, name,
                    {"epsilon": float(_a(attrs, "eps", 1e-3) or 1e-3),
                     "momentum": float(_a(attrs, "momentum", 0.9) or 0.9)})))
            elif op == "Pooling":
                ptype = attrs.get("pool_type", "max")
                if str(attrs.get("global_pool", "False")) in ("True", "1"):
                    onnx_op = "GlobalMaxPool" if ptype == "max" else \
                        "GlobalAveragePool"
                    nodes_pb.append(_f_bytes(1, _node(onnx_op, ins, outs,
                                                      name, {})))
                else:
                    kernel = tuple(_a(attrs, "kernel"))
                    stride = tuple(_a(attrs, "stride", kernel) or kernel)
                    pad = tuple(_a(attrs, "pad", (0,) * len(kernel)) or
                                (0,) * len(kernel))
                    onnx_op = "MaxPool" if ptype == "max" else "AveragePool"
                    nodes_pb.append(_f_bytes(1, _node(
                        onnx_op, ins, outs, name,
                        {"kernel_shape": list(kernel),
                         "strides": list(stride), "pads": list(pad) * 2})))
            elif op in ("softmax", "SoftmaxOutput", "log_softmax"):
                onnx_op = "LogSoftmax" if op == "log_softmax" else "Softmax"
                nodes_pb.append(_f_bytes(1, _node(
                    onnx_op, ins[:1], outs, name,
                    {"axis": int(_a(attrs, "axis", -1) or -1)})))
            elif op in ("Flatten", "flatten"):
                nodes_pb.append(_f_bytes(1, _node("Flatten", ins, outs, name,
                                                  {"axis": 1})))
            elif op == "Dropout":
                nodes_pb.append(_f_bytes(1, _node("Dropout", ins, outs, name,
                                                  {})))
            elif op in ("broadcast_add", "elemwise_add", "_plus"):
                nodes_pb.append(_f_bytes(1, _node("Add", ins, outs, name, {})))
            elif op in ("broadcast_sub", "elemwise_sub"):
                nodes_pb.append(_f_bytes(1, _node("Sub", ins, outs, name, {})))
            elif op in ("broadcast_mul", "elemwise_mul"):
                nodes_pb.append(_f_bytes(1, _node("Mul", ins, outs, name, {})))
            elif op in ("broadcast_div", "elemwise_div"):
                nodes_pb.append(_f_bytes(1, _node("Div", ins, outs, name, {})))
            elif op == "concat":
                nodes_pb.append(_f_bytes(1, _node(
                    "Concat", ins, outs, name,
                    {"axis": int(_a(attrs, "dim", 1) or 1)})))
            elif op in ("reshape", "Reshape"):
                shape_name = name + "_shape"
                shp = _np.asarray(_a(attrs, "shape"), _np.int64)
                inits_pb.append(_f_bytes(5, _tensor(shape_name, shp)))
                nodes_pb.append(_f_bytes(1, _node(
                    "Reshape", [ins[0], shape_name], outs, name, {})))
            elif op in ("transpose",):
                axes = _a(attrs, "axes")
                nodes_pb.append(_f_bytes(1, _node(
                    "Transpose", ins, outs, name,
                    {"perm": list(axes)} if axes else {})))
            elif op == "relu":
                nodes_pb.append(_f_bytes(1, _node("Relu", ins, outs, name, {})))
            elif op == "sigmoid":
                nodes_pb.append(_f_bytes(1, _node("Sigmoid", ins, outs, name,
                                                  {})))
            elif op == "tanh":
                nodes_pb.append(_f_bytes(1, _node("Tanh", ins, outs, name, {})))
            else:
                raise MXNetError(
                    "onnx export: op %r has no ONNX mapping yet (supported: "
                    "FC/Conv/BN/Pool/activations/elemwise/concat/reshape/"
                    "transpose/softmax/dropout/flatten)" % op)

    finally:
        # the sink is module-global: ALWAYS detach it, even when
        # an unsupported op raises mid-walk (and never leave a
        # stale set for a concurrent/next export to pollute)
        _ref_sink = None
    # a param may be skipped only if NO emitted node consumes it directly
    # (a weight shared between a flatten=False MatMul and any direct use
    # must still be stored); direct_refs was filled at _node-emission time
    for pname in param_nodes:
        if pname in consumed_only_transposed and pname not in direct_refs:
            continue    # only its _T form is referenced; don't store twice
        inits_pb.append(_f_bytes(5, _tensor(pname, params[pname])))
    for pname, arr in params.items():
        if pname.endswith("_T") and pname not in param_nodes:
            inits_pb.append(_f_bytes(5, _tensor(pname, arr)))

    for node, idx in sym._heads:
        outputs_pb.append(_f_bytes(12, _value_info(out_name(node, idx), ())))

    graph = b"".join(nodes_pb) + _f_str(2, "mxnet_tpu") + \
        b"".join(inits_pb) + b"".join(inputs_pb) + b"".join(outputs_pb)
    opset = _f_str(1, "") + _f_varint(2, opset_version)
    model = _f_varint(1, 8)                      # ir_version 8
    model += _f_str(2, "mxnet_tpu") + _f_str(3, "3.0")
    model += _f_bytes(7, graph)
    model += _f_bytes(8, opset)
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    return onnx_file_path


# ---------------------------------------------------------------------------
# onnx graph -> mx symbol
# ---------------------------------------------------------------------------


def _sym_pads(attrs, what):
    """mxnet pads are symmetric (begin == end); reject silently-lossy
    asymmetric ONNX pads instead of truncating them."""
    pads = attrs.get("pads")
    if pads is not None:
        half = len(pads) // 2
        if list(pads[:half]) != list(pads[half:]):
            raise MXNetError(
                "onnx import: %s with asymmetric pads %s is not supported "
                "(mxnet pads are begin==end)" % (what, pads))



_IMPORT_SIMPLE = {"Relu": ("Activation", {"act_type": "relu"}),
                  "Sigmoid": ("Activation", {"act_type": "sigmoid"}),
                  "Tanh": ("Activation", {"act_type": "tanh"}),
                  "Softplus": ("Activation", {"act_type": "softrelu"})}


def _claim_layout(registry, name, want_t):
    """Order-independent shared-weight layout check: every consumer of an
    initializer must agree on whether it gets transposed."""
    prev = registry.setdefault(name, want_t)
    if prev != want_t:
        raise MXNetError(
            "onnx import: weight %r is shared by nodes with conflicting "
            "layouts (transB / MatMul-transposed mix)" % name)


def import_model(onnx_file_path: str):
    """Reference: onnx2mx import_model → (sym, arg_params, aux_params)."""
    from .. import ndarray as nd
    from .. import symbol as sym_mod

    with open(onnx_file_path, "rb") as f:
        buf = f.read()
    graph = None
    for field, wire, val in _scan(buf):
        if field == 7:
            graph = val
    if graph is None:
        raise MXNetError("onnx import: no graph in %r" % onnx_file_path)

    nodes = []
    inits: Dict[str, _np.ndarray] = {}
    g_inputs: List[Tuple[str, Tuple[int, ...]]] = []
    g_outputs: List[str] = []
    for field, wire, val in _scan(graph):
        if field == 1:
            nodes.append(_parse_node(val))
        elif field == 5:
            nm, arr = _parse_tensor(val)
            inits[nm] = arr
        elif field == 11:
            g_inputs.append(_parse_value_info(val))
        elif field == 12:
            g_outputs.append(_parse_value_info(val)[0])

    env: Dict[str, Any] = {}
    transposed_weights: set = set()
    weight_layout: Dict[str, bool] = {}   # name -> wants transpose
    for nm, shape in g_inputs:
        env[nm] = sym_mod.Variable(nm)
    arg_params: Dict[str, Any] = {}
    aux_params: Dict[str, Any] = {}

    def var_of(nm):
        if nm not in env:
            env[nm] = sym_mod.Variable(nm)
            if nm in inits:
                (aux_params if ("moving_" in nm or "running_" in nm)
                 else arg_params)[nm] = nd.array(inits[nm])
        return env[nm]

    last = None
    for op_type, ins, outs, name, attrs in nodes:
        if op_type == "Flatten" and name.endswith("_flatten"):
            env[outs[0]] = sym_mod.flatten(var_of(ins[0]))
        elif op_type == "Gemm":
            if ins[1] not in inits:
                raise MXNetError("onnx import: Gemm weight %r must be an "
                                 "initializer (dynamic weights are not "
                                 "supported)" % ins[1])
            alpha = float(attrs.get("alpha", 1.0))
            beta = float(attrs.get("beta", 1.0))
            if int(attrs.get("transA", 0)) != 0 or alpha != 1.0 \
                    or beta != 1.0:
                raise MXNetError(
                    "onnx import: Gemm with transA/alpha/beta != defaults "
                    "is not supported (got transA=%s alpha=%s beta=%s)"
                    % (attrs.get("transA", 0), alpha, beta))
            want_t = int(attrs.get("transB", 0)) == 0  # ONNX default is 0
            _claim_layout(weight_layout, ins[1], want_t)
            if want_t and ins[1] not in transposed_weights:
                # weight stored (in, out): transpose into FC layout ONCE
                inits[ins[1]] = _np.ascontiguousarray(inits[ins[1]].T)
                transposed_weights.add(ins[1])
            w = inits[ins[1]]
            fc_in = [var_of(ins[0]), var_of(ins[1])]
            if len(ins) > 2:
                fc_in.append(var_of(ins[2]))
            env[outs[0]] = sym_mod.FullyConnected(
                *fc_in, num_hidden=int(w.shape[0]),
                no_bias=len(ins) <= 2, name=name)
        elif op_type == "Conv":
            w = inits[ins[1]]
            _sym_pads(attrs, "Conv")
            conv_in = [var_of(ins[0]), var_of(ins[1])]
            if len(ins) > 2:
                conv_in.append(var_of(ins[2]))
            out = sym_mod.Convolution(
                *conv_in,
                kernel=tuple(attrs["kernel_shape"]),
                stride=tuple(attrs.get("strides",
                                       (1,) * len(attrs["kernel_shape"]))),
                pad=tuple(attrs.get("pads",
                                    [0] * 2 * len(attrs["kernel_shape"]))
                          [:len(attrs["kernel_shape"])]),
                dilate=tuple(attrs.get("dilations",
                                       (1,) * len(attrs["kernel_shape"]))),
                num_filter=int(w.shape[0]),
                num_group=int(attrs.get("group", 1)),
                no_bias=len(ins) <= 2, name=name)
            env[outs[0]] = out
        elif op_type in _IMPORT_SIMPLE:
            mx_op, extra = _IMPORT_SIMPLE[op_type]
            env[outs[0]] = getattr(sym_mod, mx_op)(var_of(ins[0]),
                                                   name=name, **extra)
        elif op_type == "BatchNormalization":
            env[outs[0]] = sym_mod.BatchNorm(
                *[var_of(i) for i in ins], name=name,
                eps=float(attrs.get("epsilon", 1e-3)),
                momentum=float(attrs.get("momentum", 0.9)),
                fix_gamma=False)
        elif op_type in ("MaxPool", "AveragePool", "GlobalMaxPool",
                         "GlobalAveragePool"):
            if not op_type.startswith("Global"):
                _sym_pads(attrs, op_type)
            if op_type.startswith("Global"):
                env[outs[0]] = sym_mod.Pooling(
                    var_of(ins[0]), kernel=(1, 1), global_pool=True,
                    pool_type="max" if "Max" in op_type else "avg",
                    name=name)
            else:
                k = tuple(attrs["kernel_shape"])
                env[outs[0]] = sym_mod.Pooling(
                    var_of(ins[0]), kernel=k,
                    stride=tuple(attrs.get("strides", k)),
                    pad=tuple(attrs.get("pads", [0] * 2 * len(k))[:len(k)]),
                    pool_type="max" if op_type == "MaxPool" else "avg",
                    name=name)
        elif op_type in ("Softmax", "LogSoftmax"):
            fn = sym_mod.log_softmax if op_type == "LogSoftmax" else \
                sym_mod.softmax
            env[outs[0]] = fn(var_of(ins[0]),
                              axis=int(attrs.get("axis", -1)), name=name)
        elif op_type == "Flatten":
            env[outs[0]] = sym_mod.flatten(var_of(ins[0]), name=name)
        elif op_type == "Dropout":
            env[outs[0]] = var_of(ins[0])      # inference: identity
        elif op_type == "MatMul":
            if ins[1] not in inits:
                raise MXNetError("onnx import: MatMul needs an initializer "
                                 "weight")
            _claim_layout(weight_layout, ins[1], True)
            # (in, out) layout from export's _T initializer -> FC layout
            if ins[1] not in transposed_weights:
                inits[ins[1]] = _np.ascontiguousarray(inits[ins[1]].T)
                transposed_weights.add(ins[1])
            w = inits[ins[1]]     # (out, in) AFTER the shared transpose
            env[outs[0]] = sym_mod.FullyConnected(
                var_of(ins[0]), var_of(ins[1]),
                num_hidden=int(w.shape[0]), no_bias=True, flatten=False,
                name=name)
        elif op_type in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": sym_mod.broadcast_add,
                  "Sub": sym_mod.broadcast_sub,
                  "Mul": sym_mod.broadcast_mul,
                  "Div": sym_mod.broadcast_div}[op_type]
            env[outs[0]] = fn(var_of(ins[0]), var_of(ins[1]), name=name)
        elif op_type == "Concat":
            env[outs[0]] = sym_mod.concat(
                *[var_of(i) for i in ins],
                dim=int(attrs.get("axis", 1)), name=name)
        elif op_type == "Reshape":
            if ins[1] not in inits:
                raise MXNetError("onnx import: Reshape shape %r must be an "
                                 "initializer (dynamic shapes are not "
                                 "supported)" % ins[1])
            shp = tuple(int(x) for x in inits[ins[1]])
            env[outs[0]] = sym_mod.reshape(var_of(ins[0]), shape=shp,
                                           name=name)
        elif op_type == "Transpose":
            env[outs[0]] = sym_mod.transpose(
                var_of(ins[0]), axes=tuple(attrs.get("perm", ())) or None,
                name=name)
        else:
            raise MXNetError("onnx import: op %r unsupported" % op_type)
        last = env[outs[0]]

    # materialize any initializer referenced by the graph into params
    for nm, arr in inits.items():
        if nm in env and nm not in arg_params and nm not in aux_params:
            (aux_params if ("moving_" in nm or "running_" in nm)
             else arg_params)[nm] = nd.array(arr)
    # return the graph's DECLARED outputs (field 12), not whichever node
    # happened to come last in the topological order
    declared = [env[o] for o in g_outputs if o in env]
    if declared:
        out_sym = declared[0] if len(declared) == 1 \
            else sym_mod.Group(declared)
    else:
        out_sym = last
    return out_sym, arg_params, aux_params


def get_model_metadata(onnx_file_path: str):
    """Reference: onnx2mx.get_model_metadata — input/output descriptors."""
    with open(onnx_file_path, "rb") as f:
        buf = f.read()
    meta = {"input_tensor_data": [], "output_tensor_data": []}
    for field, wire, val in _scan(buf):
        if field == 7:
            for f2, w2, v2 in _scan(val):
                if f2 == 11:
                    meta["input_tensor_data"].append(_parse_value_info(v2))
                elif f2 == 12:
                    meta["output_tensor_data"].append(_parse_value_info(v2))
    return meta
