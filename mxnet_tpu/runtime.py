"""mx.runtime — build/runtime feature discovery.

Reference: ``python/mxnet/runtime.py`` (class Feature, feature_list,
Features.is_enabled — backed by libinfo.cc's compile-time flag table).

The rebuild's "build flags" are runtime properties of the JAX/XLA stack:
which PJRT backends are reachable, which dtypes the compiler supports,
and which subsystems this package ships.  Names keep the reference's
spelling where a meaningful mapping exists (CUDA→TPU, MKLDNN→XLA CPU,
OPENCV→PIL, ...) so scripts probing `is_enabled('...')` keep working.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List

__all__ = ["Feature", "Features", "feature_list", "features"]


class Feature:
    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return "[%s %s]" % ("✔" if self.enabled else "✖", self.name)


def _accelerator_reachable() -> bool:
    """True if a non-CPU PJRT backend is registered and healthy; never
    blocks on a wedged tunnel (subprocess probe with timeout)."""
    from .base import cpu_pinned_by_user, probe_accelerator
    if cpu_pinned_by_user():
        return False
    return bool(probe_accelerator(60))


def _have(mod: str) -> bool:
    import importlib.util
    return importlib.util.find_spec(mod) is not None


def feature_list() -> List[Feature]:
    """Check the run-time features (reference: runtime.feature_list)."""
    import jax
    feats = OrderedDict()
    feats["TPU"] = _accelerator_reachable()
    feats["CUDA"] = False           # this build targets TPU via XLA
    feats["CUDNN"] = False
    feats["XLA"] = True
    feats["PALLAS"] = _have("jax.experimental.pallas")
    feats["BLAS_OPEN"] = True       # XLA:CPU's dot lowering
    feats["MKLDNN"] = True          # role: XLA:CPU fused kernels
    feats["OPENCV"] = _have("PIL")  # PIL fills the codec role
    feats["F16C"] = True
    feats["BF16"] = True            # MXU-native
    feats["INT64_TENSOR_SIZE"] = jax.config.jax_enable_x64
    feats["SIGNAL_HANDLER"] = False
    feats["PROFILER"] = True        # mx.profiler over jax.profiler
    feats["DIST_KVSTORE"] = True    # jax.distributed collectives
    feats["SSE"] = True
    feats["LAPACK"] = _have("scipy")
    feats["RECORDIO"] = True
    try:
        from . import _native
        _native.load("recordio")
        feats["NATIVE_RECORDIO"] = True
    except OSError:
        feats["NATIVE_RECORDIO"] = False
    return [Feature(k, v) for k, v in feats.items()]


class Features(Dict[str, Feature]):
    """Dict-like view with is_enabled (reference: runtime.Features)."""

    instance = None

    def __init__(self):
        super().__init__([(f.name, f) for f in feature_list()])

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name: str) -> bool:
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature %r does not exist" % feature_name)
        return self[feature_name].enabled


def features() -> Features:
    if Features.instance is None:
        Features.instance = Features()
    return Features.instance
