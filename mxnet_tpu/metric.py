"""Evaluation metrics.

Reference: python/mxnet/metric.py (class EvalMetric, Accuracy, TopKAccuracy,
F1, MCC, Perplexity, MAE, MSE, RMSE, CrossEntropy, NegativeLogLikelihood,
PearsonCorrelation, Loss, CompositeEvalMetric, CustomMetric, np(), create()).
Gluon 2.x re-exports this surface as gluon.metric.

Device-side accumulation (ISSUE 3 tentpole c): the hot fit-loop metrics
(Accuracy, MSE/MAE/RMSE, Loss, CrossEntropy, Perplexity) keep their running
sum/count as DEVICE scalars, updated inside one jitted accumulate per batch
— update() never calls asnumpy(), so host dispatch runs ahead of the device
instead of syncing every batch.  The host transfer is deferred to get(),
which drains the device accumulators into the classic
``sum_metric``/``num_inst`` fields (reference semantics preserved; the
host-numpy path still serves numpy/list inputs and the long-tail metrics).
"""
from __future__ import annotations

import functools
import math
from typing import List, Optional, Sequence, Union

import numpy as _np
import jax
import jax.numpy as jnp

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss",
           "CustomMetric", "np", "create", "check_label_shapes",
           "VOCMApMetric", "VOC07MApMetric", "Torch", "Caffe"]

_METRIC_REGISTRY = {}


def register(klass):
    _METRIC_REGISTRY[klass.__name__.lower()] = klass
    return klass


def _as_numpy(x):
    # the documented EAGER FALLBACK: metrics whose inputs are already host
    # arrays (or whose math is host-only) come through here; device-array
    # callers take the _accumulate path and never reach this sync
    if hasattr(x, "asnumpy"):
        return x.asnumpy()     # mxlint: disable=host-sync-in-hot-path
    return _np.asarray(x)      # mxlint: disable=host-sync-in-hot-path


def _device_val(x):
    """The jax.Array behind a device-resident dense input, else None (the
    caller then takes the host-numpy path)."""
    if isinstance(x, jax.Array):
        return x
    if getattr(x, "stype", None) == "default" and hasattr(x, "_jax"):
        return x._jax
    return None


def check_label_shapes(labels, preds, shape: bool = False):
    """Reference: metric.check_label_shapes."""
    if not shape:
        n_label, n_pred = len(labels), len(preds)
    else:
        n_label = labels.shape[0]
        n_pred = preds.shape[0]
    if n_label != n_pred:
        raise ValueError("Shape of labels %d does not match shape of "
                         "predictions %d" % (n_label, n_pred))


class EvalMetric:
    """Base accumulator (reference: class EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._dev_sum = None
        self._dev_inst = None

    # -- device-side accumulation -----------------------------------------
    def _accumulate(self, kernel, *arrays):
        """Fold one batch into the device accumulators: ONE jitted
        dispatch, no host sync (kernel(sum, count, *arrays) -> (sum',
        count'))."""
        # home everything on the first array's device (group2ctx heads may
        # produce outputs on another device than the labels/accumulators)
        dev = next(iter(arrays[0].devices()))
        arrays = tuple(a if a.devices() == {dev} else jax.device_put(a, dev)
                       for a in arrays)
        ds = getattr(self, "_dev_sum", None)
        if ds is None:
            ds = jax.device_put(jnp.zeros((), jnp.float32), dev)
            di = jax.device_put(jnp.zeros((), jnp.int32), dev)
        else:
            di = self._dev_inst
            if ds.devices() != {dev}:
                ds = jax.device_put(ds, dev)
                di = jax.device_put(di, dev)
        from .engine import engine as _engine
        from . import telemetry as _telemetry
        with _telemetry.phase("metric_update"):
            _engine.count_dispatch()
            self._dev_sum, self._dev_inst = kernel(ds, di, *arrays)

    def _trace_kernel(self):
        """(kernel, argspec) for folding this metric's accumulate into a
        whole-step compiled program (ISSUE 7; mxnet_tpu.step) — the same
        jitted kernel :meth:`_accumulate` dispatches, inlined into the
        step's single XLA program with the device accumulators carried as
        donated state.  argspec names the operand order after (sum,
        count): 'pred_label', 'label_pred' or 'loss'.  None = this metric
        has no pure device kernel; callers accumulate eagerly from the
        step's returned outputs instead."""
        return None

    def _drain_device(self):
        """Host sync point: move the device accumulators into the classic
        sum_metric/num_inst fields (called by get()).  This is the ONE
        deliberate metric sync per drain — the telemetry phase span makes
        its cost visible (epoch-end drains are cheap; one inside the step
        loop would light up the per-phase breakdown)."""
        ds = getattr(self, "_dev_sum", None)
        if ds is not None:
            from . import telemetry as _telemetry
            with _telemetry.phase("metric_drain"):
                self.sum_metric += float(_np.asarray(ds))
                self.num_inst += int(_np.asarray(self._dev_inst))
                self._dev_sum = None
                self._dev_inst = None

    def get(self):
        self._drain_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    """Manage multiple metrics as one (reference: CompositeEvalMetric)."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name) if isinstance(name, str) else names.extend(name)
            values.append(value) if not isinstance(value, list) \
                else values.extend(value)
        return (names, values)


def _census(name):
    """Light-mode program-census wrapper for the device metric kernels
    (ISSUE 10): jax.jit dispatch stays on the hot accumulate path, the
    registry sees each kernel's (re)trace count and compile time."""
    def deco(fn):
        from .programs import register_program
        return register_program(name, fn, mode="light")
    return deco


@functools.lru_cache(maxsize=None)
def _acc_kernel(axis):
    @_census("metric.accuracy")
    def k(s, n, pred, label):
        if pred.ndim > label.ndim:
            pred = jnp.argmax(pred, axis=axis)
        p = pred.reshape(-1).astype(jnp.int32)
        l = label.reshape(-1).astype(jnp.int32)
        return s + (p == l).sum().astype(jnp.float32), n + l.size
    return k


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def _trace_kernel(self):
        return _acc_kernel(self.axis), "pred_label"

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pj, lj = _device_val(pred), _device_val(label)
            if pj is not None and lj is not None:
                n_pred = pj.size // (pj.shape[self.axis]
                                     if pj.ndim > lj.ndim else 1)
                if n_pred != lj.size:
                    raise ValueError(
                        "Shape of labels %d does not match shape of "
                        "predictions %d" % (lj.size, n_pred))
                self._accumulate(_acc_kernel(self.axis), pj, lj)
                continue
            pred = _as_numpy(pred)
            label = _as_numpy(label)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype(_np.int64).ravel()
            label = label.astype(_np.int64).ravel()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert top_k > 1, "use Accuracy for top_k=1"
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_np.int64)
            assert pred.ndim <= 2, "Predictions should be no more than 2 dims"
            topk = _np.argsort(pred.astype(_np.float64), axis=-1)
            num_classes = pred.shape[-1]
            depth = min(self.top_k, num_classes)
            if pred.ndim == 1:
                self.sum_metric += float(
                    (topk[-depth:] == label).any())
                self.num_inst += 1
            else:
                for k in range(1, depth + 1):
                    self.sum_metric += float(
                        (topk[:, -k] == label.ravel()).sum())
                self.num_inst += label.shape[0]


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.F1, average='macro'|'micro')."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        self.average = average
        self._tp = self._fp = self._fn = 0.0
        self._scores: List[float] = []
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_np.int64).ravel()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, axis=-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype(_np.int64)
            tp = float(((pred == 1) & (label == 1)).sum())
            fp = float(((pred == 1) & (label == 0)).sum())
            fn = float(((pred == 0) & (label == 1)).sum())
            if self.average == "micro":
                self._tp += tp
                self._fp += fp
                self._fn += fn
            else:
                prec = tp / (tp + fp) if tp + fp else 0.0
                rec = tp / (tp + fn) if tp + fn else 0.0
                f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
                self._scores.append(f1)
            self.num_inst += 1

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        self._scores = []
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        if self.average == "micro":
            prec = self._tp / (self._tp + self._fp) if self._tp + self._fp else 0
            rec = self._tp / (self._tp + self._fn) if self._tp + self._fn else 0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
            return (self.name, f1)
        return (self.name, sum(self._scores) / len(self._scores))


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient (reference: metric.MCC)."""

    def __init__(self, name="mcc", output_names=None, label_names=None):
        self._tp = self._fp = self._tn = self._fn = 0.0
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_np.int64).ravel()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = _np.argmax(pred, axis=-1).ravel()
            else:
                pred = (pred.ravel() > 0.5).astype(_np.int64)
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    def reset(self):
        self._tp = self._fp = self._tn = self._fn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        tp, fp, tn, fn = self._tp, self._fp, self._tn, self._fn
        denom = math.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return (self.name, ((tp * tn) - (fp * fn)) / denom if denom else 0.0)


@functools.lru_cache(maxsize=None)
def _ppl_kernel(ignore_label):
    @_census("metric.perplexity")
    def k(s, n, pred, label):
        p = pred.reshape(-1, pred.shape[-1]).astype(jnp.float32)
        l = label.reshape(-1).astype(jnp.int32)
        probs = jnp.take_along_axis(p, l[:, None], axis=-1)[:, 0]
        count = l.shape[0]
        if ignore_label is not None:
            ign = (l == int(ignore_label))
            probs = jnp.where(ign, 1.0, probs)
            count = count - ign.sum()
        loss = -jnp.sum(jnp.log(jnp.maximum(1e-10, probs)))
        return s + loss.astype(jnp.float32), n + count
    return k


@register
class Perplexity(EvalMetric):
    """exp(mean NLL) (reference: metric.Perplexity; ignore_label skips
    padding tokens — the PTB LM eval path)."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def _trace_kernel(self):
        return _ppl_kernel(self.ignore_label), "pred_label"

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            pj, lj = _device_val(pred), _device_val(label)
            if pj is not None and lj is not None:
                self._accumulate(_ppl_kernel(self.ignore_label), pj, lj)
                continue
            pred = _as_numpy(pred).astype(_np.float64)
            label = _as_numpy(label).astype(_np.int64).reshape(-1)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= _np.sum(_np.log(_np.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        self._drain_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@functools.lru_cache(maxsize=None)
def _regression_kernel(squared):
    @_census("metric.mse" if squared else "metric.mae")
    def k(s, n, label, pred):
        if label.ndim == 1:
            label = label.reshape(-1, 1)
        if pred.ndim == 1:
            pred = pred.reshape(-1, 1)
        diff = label.astype(jnp.float32) - pred.astype(jnp.float32)
        err = (diff * diff).mean() if squared else jnp.abs(diff).mean()
        return s + err, n + 1
    return k


class _RegressionMetric(EvalMetric):
    """Shared MAE/MSE accumulation (device path + host fallback)."""

    _squared = False

    def _trace_kernel(self):
        return _regression_kernel(self._squared), "label_pred"

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            lj, pj = _device_val(label), _device_val(pred)
            if lj is not None and pj is not None:
                self._accumulate(_regression_kernel(self._squared), lj, pj)
                continue
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            err = ((label - pred) ** 2) if self._squared \
                else _np.abs(label - pred)
            self.sum_metric += float(err.mean())
            self.num_inst += 1


@register
class MAE(_RegressionMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class MSE(_RegressionMetric):
    _squared = True

    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        self._drain_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@functools.lru_cache(maxsize=None)
def _ce_kernel(eps):
    @_census("metric.cross_entropy")
    def k(s, n, label, pred):
        l = label.reshape(-1).astype(jnp.int32)
        prob = jnp.take_along_axis(pred.astype(jnp.float32), l[:, None],
                                   axis=-1)[:, 0]
        return (s + (-jnp.log(prob + eps)).sum().astype(jnp.float32),
                n + l.shape[0])
    return k


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def _trace_kernel(self):
        return _ce_kernel(self.eps), "label_pred"

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            lj, pj = _device_val(label), _device_val(pred)
            if lj is not None and pj is not None and pj.ndim == 2:
                assert lj.size == pj.shape[0]
                self._accumulate(_ce_kernel(self.eps), lj, pj)
                continue
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label.astype(_np.int64)]
            self.sum_metric += float((-_np.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self.sum_metric += float(_np.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@_census("metric.loss")
def _loss_kernel(s, n, pred):
    return s + pred.sum().astype(jnp.float32), n + pred.size


@register
class Loss(EvalMetric):
    """Mean of a loss output stream (reference: metric.Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def _trace_kernel(self):
        return _loss_kernel, "loss"

    def update(self, _, preds):
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        for pred in preds:
            pj = _device_val(pred)
            if pj is not None:
                self._accumulate(_loss_kernel, pj)
                continue
            loss = float(_as_numpy(pred).sum())
            self.sum_metric += loss
            self.num_inst += int(_np.prod(_as_numpy(pred).shape))


@register
class Torch(Loss):
    """Deprecated alias metric for torch criterion outputs (reference:
    metric.Torch — Loss under another name)."""

    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)


@register
class Caffe(Loss):
    """Deprecated alias metric for caffe criterion outputs (reference:
    metric.Caffe — Loss under another name)."""

    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names,
                         label_names=label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        name = name or getattr(feval, "__name__", "custom")
        if name.startswith("<"):
            name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        preds = preds if isinstance(preds, (list, tuple)) else [preds]
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_numpy(label), _as_numpy(pred))
            if isinstance(reval, tuple):
                num_inst, sum_metric = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


@register
class VOCMApMetric(EvalMetric):
    """PASCAL-VOC mean average precision (reference: GluonCV
    VOCMApMetric / example/ssd evaluate.py MApMetric).

    update(labels, preds): labels (B, M, 5+) rows [cls, x1, y1, x2, y2]
    padded with cls=-1; preds (B, N, 6) rows [cls, score, x1, y1, x2, y2]
    with suppressed rows -1 (MultiBoxDetection's output)."""

    def __init__(self, iou_thresh=0.5, class_names=None, use_07_metric=False,
                 name="mAP"):
        self.iou_thresh = iou_thresh
        self.class_names = class_names
        self.use_07_metric = use_07_metric
        super().__init__(name)

    def reset(self):
        self._records = {}   # cls -> list[(score, is_tp)]
        self._n_gt = {}      # cls -> count
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        # detection mAP is host-side by design: per-class score sorting +
        # greedy box matching have no fixed-shape device formulation
        for lab, pred in zip(labels, preds):
            lab = _as_numpy(lab)
            pred = _as_numpy(pred)
            for b in range(lab.shape[0]):
                self._update_one(lab[b], pred[b])

    def _update_one(self, lab, pred):
        gts = lab[lab[:, 0] >= 0]
        for c in gts[:, 0].astype(int):
            self._n_gt[c] = self._n_gt.get(c, 0) + 1
        dets = pred[pred[:, 0] >= 0]
        dets = dets[_np.argsort(-dets[:, 1])]
        matched = _np.zeros(len(gts), bool)
        for det in dets:
            c = int(det[0])
            # VOC protocol: pick the overall-best-IoU gt of this class; a
            # second detection of an ALREADY-matched gt is a false positive
            # (it must not fall through to a worse gt)
            best_iou, best_j = 0.0, -1
            for j, gt in enumerate(gts):
                if int(gt[0]) != c:
                    continue
                iou = self._iou(det[2:6], gt[1:5])
                if iou > best_iou:
                    best_iou, best_j = iou, j
            tp = (best_j >= 0 and best_iou >= self.iou_thresh
                  and not matched[best_j])
            if tp:
                matched[best_j] = True
            self._records.setdefault(c, []).append((float(det[1]), tp))

    @staticmethod
    def _iou(a, b):
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
        inter = ix * iy
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def _average_precision(self, recs, n_gt):
        if not recs or n_gt == 0:
            return 0.0
        recs = sorted(recs, key=lambda r: -r[0])
        tps = _np.cumsum([r[1] for r in recs])
        fps = _np.cumsum([not r[1] for r in recs])
        rec = tps / n_gt
        prec = tps / _np.maximum(tps + fps, 1e-12)
        if self.use_07_metric:      # 11-point interpolation
            ap = 0.0
            for t in _np.arange(0.0, 1.1, 0.1):
                p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                ap += p / 11.0
            return float(ap)
        # VOC10+/COCO-style: integrate the precision envelope
        mrec = _np.concatenate([[0.0], rec, [1.0]])
        mpre = _np.concatenate([[0.0], prec, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = _np.where(mrec[1:] != mrec[:-1])[0]
        return float(_np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))

    def get(self):
        classes = sorted(self._n_gt)
        if not classes:
            return self.name, float("nan")
        aps = [self._average_precision(self._records.get(c, []),
                                       self._n_gt[c]) for c in classes]
        return self.name, float(_np.mean(aps))


@register
class VOC07MApMetric(VOCMApMetric):
    """11-point interpolated VOC2007 mAP (reference: VOC07MApMetric)."""

    def __init__(self, iou_thresh=0.5, class_names=None, name="mAP07"):
        super().__init__(iou_thresh, class_names, use_07_metric=True,
                         name=name)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    """Reference: metric.create — by name, callable, list, or instance."""
    if callable(metric) and not isinstance(metric, type):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, CompositeEvalMetric):
        return metric
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        key = metric.lower()
        aliases = {"acc": "accuracy", "ce": "crossentropy",
                   "nll_loss": "negativeloglikelihood",
                   "top_k_accuracy": "topkaccuracy", "top_k_acc": "topkaccuracy",
                   "pearson_correlation": "pearsoncorrelation"}
        key = aliases.get(key, key)
        if key in _METRIC_REGISTRY:
            return _METRIC_REGISTRY[key](*args, **kwargs)
    if isinstance(metric, type) and issubclass(metric, EvalMetric):
        return metric(*args, **kwargs)
    raise ValueError("Metric must be a callable, name, or EvalMetric; got %r"
                     % (metric,))
