"""AMP op lists.

Reference: ``python/mxnet/contrib/amp/lists/symbol_fp16.py`` — the reference
partitions its op surface into FP16_FUNCS (always narrow), FP32_FUNCS
(always wide), FP16_FP32_FUNCS (either), WIDEST_TYPE_CASTS (match inputs),
and CONDITIONAL_FP32_FUNCS (wide for particular attribute values).

TPU-first: the narrow dtype defaults to **bfloat16** (MXU-native; same
exponent range as fp32, so dynamic loss scaling is unnecessary), and the
lists name this rebuild's canonical op names.  Anything in neither list
runs in whatever dtype its inputs already carry — XLA type-propagates the
rest of the graph, so only dtype *boundaries* need declaring.
"""

# MXU-bound ops: always cast fp32 inputs down to the target dtype — these are
# where the FLOPs are and where bf16 doubles throughput.
TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "RNN",
    "dot", "batch_dot", "einsum",
    "linalg_gemm", "linalg_gemm2",
    "multi_head_attention",
    "_contrib_interleaved_matmul_encdec_qk",
    "_contrib_interleaved_matmul_encdec_valatt",
    "_contrib_interleaved_matmul_selfatt_qk",
    "_contrib_interleaved_matmul_selfatt_valatt",
]

# Numerically sensitive ops: always promote narrow float inputs to fp32
# (softmax/log/exp accumulate in ways that overflow/cancel in 8-bit-mantissa
# bf16; norms divide by small variances).
FP32_OPS = [
    "softmax", "log_softmax", "softmin", "masked_softmax",
    "masked_log_softmax", "softmax_cross_entropy", "SoftmaxOutput",
    "CTCLoss",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "RMSNorm",
    "L2Normalization", "norm", "moments", "var", "std",
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "erfinv", "gammaln", "digamma", "polygamma", "gammainc", "gammaincc",
    "logsumexp", "cumsum", "cumprod", "linalg_potrf", "linalg_potri",
    "linalg_sumlogdiag", "linalg_det", "linalg_slogdet", "linalg_inverse",
    "linalg_syevd",
]

# Multi-input elementwise ops: if inputs mix float widths, cast all to the
# widest so XLA doesn't silently truncate one operand.
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "broadcast_hypot", "broadcast_mod",
    "arctan2", "copysign", "logaddexp", "hypot", "ldexp", "nextafter",
    "where", "lerp", "concat", "stack", "heaviside",
]

# (op_name, param_name, [values]) -> run in fp32 when the attribute matches
# (reference: CONDITIONAL_FP32_FUNCS, e.g. softrelu activation).
CONDITIONAL_FP32_OPS = [
    ("Activation", "act_type", ["softrelu"]),
    ("LeakyReLU", "act_type", ["selu", "elu"]),
]
