"""mx.amp — automatic mixed precision.

Reference: ``python/mxnet/contrib/amp/amp.py`` (init, init_trainer,
scale_loss, unscale, convert_hybrid_block) and its op lists
(``lists/symbol_fp16.py``).

TPU-first design: the reference rewrites graphs by monkey-patching every op
function and inserting ``amp_cast`` symbol nodes.  Here **all** op traffic —
eager, autograd, and hybridize tracing — flows through one dispatcher
(``ndarray.invoke``), so AMP is a single cast hook at that chokepoint:
ops on the *target* list get narrow inputs, ops on the *fp32* list get wide
inputs, *widest* ops get type-matched inputs, and XLA propagates dtypes
through everything else (then fuses the casts into adjacent kernels, so the
inserted converts are free in practice).

The default target dtype is ``bfloat16``: MXU-native and fp32-exponent-range,
so loss scaling is a no-op by default (``LossScaler(init_scale=1)``).
``float16`` is supported with the reference's dynamic loss-scaling algorithm
for parity.
"""
from __future__ import annotations

import functools
from contextlib import contextmanager
from typing import List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

from . import lists

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "LossScaler",
           "convert_hybrid_block", "lists"]

# Consulted by ndarray._invoke_impl on every dispatch; None = AMP off.
STATE: Optional["_AmpState"] = None

# Thread-local override stack: per-block subgraph properties (amp_bf16 /
# amp_float16) scope a policy to ONE block's trace without touching the
# process-wide STATE other threads read concurrently.
import threading as _threading  # noqa: E402

_TLS = _threading.local()


def current_state() -> Optional["_AmpState"]:
    """The effective AMP policy for this thread: innermost scoped override
    first, else the process-wide STATE."""
    stack = getattr(_TLS, "stack", None)
    if stack:
        return stack[-1]
    return STATE


class state_scope:
    """Push a scoped policy (or None to disable AMP inside the scope)."""

    def __init__(self, state: Optional["_AmpState"]):
        self._state = state

    def __enter__(self):
        if not hasattr(_TLS, "stack"):
            _TLS.stack = []
        _TLS.stack.append(self._state)
        return self

    def __exit__(self, *exc):
        _TLS.stack.pop()
        return False


_NARROW = (jnp.bfloat16, jnp.float16)


class _AmpState:
    __slots__ = ("target_dtype", "target_ops", "fp32_ops", "widest_ops",
                 "conditional_fp32")

    def __init__(self, target_dtype, target_ops, fp32_ops, widest_ops,
                 conditional_fp32):
        self.target_dtype = target_dtype
        self.target_ops = frozenset(target_ops)
        self.fp32_ops = frozenset(fp32_ops)
        self.widest_ops = frozenset(widest_ops)
        # {op_name: (param_name, frozenset(values))}
        self.conditional_fp32 = {name: (pname, frozenset(vals))
                                 for name, pname, vals in conditional_fp32}

    def cast_inputs(self, op_name: str, params: dict, jax_in: list) -> list:
        """Apply the op's dtype policy to its unwrapped jax.Array inputs."""
        if op_name in self.target_ops:
            return [self._to(x, self.target_dtype) for x in jax_in]
        if op_name in self.fp32_ops:
            return [self._up(x) for x in jax_in]
        cond = self.conditional_fp32.get(op_name)
        if cond is not None and str(params.get(cond[0])) in cond[1]:
            return [self._up(x) for x in jax_in]
        if op_name in self.widest_ops:
            floats = [x.dtype for x in jax_in
                      if isinstance(x, jnp.ndarray) and
                      jnp.issubdtype(x.dtype, jnp.floating)]
            if len(set(floats)) > 1:
                widest = functools.reduce(jnp.promote_types, floats)
                return [self._to(x, widest) for x in jax_in]
        return jax_in

    @staticmethod
    def _to(x, dtype):
        if isinstance(x, jnp.ndarray) and \
                jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dtype:
            return x.astype(dtype)
        return x

    @staticmethod
    def _up(x):
        if isinstance(x, jnp.ndarray) and x.dtype in _NARROW:
            return x.astype(jnp.float32)
        return x


def make_state(target_dtype="bfloat16", target_dtype_ops=None, fp32_ops=None,
               widest_dtype_ops=None, conditional_fp32_ops=None) -> "_AmpState":
    """Build a policy state without installing it (used by amp.init and by
    the per-block subgraph properties)."""
    dt = _np.dtype(jnp.bfloat16) if str(target_dtype) == "bfloat16" \
        else _np.dtype(target_dtype)
    if dt not in (_np.dtype(jnp.bfloat16), _np.dtype("float16")):
        raise ValueError("AMP target_dtype must be bfloat16 or float16, "
                         "got %s" % target_dtype)
    return _AmpState(
        dt,
        lists.TARGET_DTYPE_OPS if target_dtype_ops is None else target_dtype_ops,
        lists.FP32_OPS if fp32_ops is None else fp32_ops,
        lists.WIDEST_TYPE_CASTS if widest_dtype_ops is None else widest_dtype_ops,
        lists.CONDITIONAL_FP32_OPS if conditional_fp32_ops is None
        else conditional_fp32_ops,
    )


def init(target_dtype="bfloat16", target_dtype_ops=None, fp32_ops=None,
         widest_dtype_ops=None, conditional_fp32_ops=None):
    """Turn AMP on (reference: amp.init).

    target_dtype: 'bfloat16' (TPU default) or 'float16'.
    The *_ops arguments override the default lists in ``amp.lists``.
    """
    global STATE
    STATE = make_state(target_dtype, target_dtype_ops, fp32_ops,
                       widest_dtype_ops, conditional_fp32_ops)


def turn_off():
    """Disable AMP casting (no reference equivalent; useful in tests)."""
    global STATE
    STATE = None


def active() -> bool:
    return STATE is not None


# -- dynamic loss scaling -----------------------------------------------------

@functools.partial(jax.jit)
def _all_finite(flat):
    ok = jnp.bool_(True)
    for g in flat:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(g)))
    return ok


class LossScaler:
    """Dynamic loss scaling (reference: amp.loss_scaler.LossScaler).

    Multiply the loss by ``loss_scale`` before backward; divide gradients
    back during the update (via the trainer's rescale_grad); on any
    non-finite gradient skip the update and halve the scale; after
    ``scale_window`` clean steps double it.
    """

    def __init__(self, init_scale=2. ** 16, scale_factor=2.,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0
        self._max_scale = 2. ** 24

    def has_overflow(self, params) -> bool:
        """Check grads of ``params`` for inf/nan (one fused jitted reduce)."""
        grads = []
        for p in params:
            for g in p.list_grad():
                grads.append(g._jax)
        if not grads:
            return False
        return not bool(_all_finite(grads))

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale = min(self.loss_scale * self._scale_factor,
                                      self._max_scale)
                self._unskipped = 0


class _StaticScaler(LossScaler):
    """bf16 needs no scaling: scale pinned at 1, overflow check skipped
    (bf16 has fp32's exponent range — overflow means the model diverged,
    and hiding that behind skipped steps would be a disservice)."""

    def __init__(self):
        super().__init__(init_scale=1.0)

    def has_overflow(self, params) -> bool:
        return False

    def update_scale(self, overflow: bool):
        pass


def init_trainer(trainer):
    """Attach a loss scaler to a Gluon Trainer (reference: amp.init_trainer).

    Wraps the trainer's update so a step with non-finite gradients is
    skipped and the scale backed off — the reference does the same via its
    patched optimizer.
    """
    if STATE is None:
        raise RuntimeError("amp.init() must be called before init_trainer()")
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        return
    scaler = _StaticScaler() if STATE.target_dtype == _np.dtype(jnp.bfloat16) \
        else LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    orig_update = trainer._update

    def _amp_update(ignore_stale_grad=False):
        live = [p for p in trainer._params if p.grad_req != "null"]
        overflow = scaler.has_overflow(live)
        if not overflow:
            orig_update(ignore_stale_grad)
        scaler.update_scale(overflow)

    trainer._update = _amp_update


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss up before ``backward()`` (reference: amp.scale_loss).

    Usage::

        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(batch_size)
    """
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    if isinstance(loss, (list, tuple)):
        yield type(loss)(l * scaler.loss_scale for l in loss)
    else:
        yield loss * scaler.loss_scale


def unscale(trainer):
    """Divide current gradients by the loss scale in place (reference:
    amp.unscale) — for gradient manipulation between backward and step."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req == "null":
            continue
        for g in p.list_grad():
            g *= inv
    # grads are now unscaled; stop the trainer from dividing again
    trainer._scale = trainer._amp_original_scale


def convert_hybrid_block(block, target_dtype="bfloat16",
                         cast_optional_params=False):
    """Cast a HybridBlock for narrow-dtype inference (reference:
    amp.convert_hybrid_block).

    Casts every parameter to ``target_dtype`` except normalization-layer
    parameters (gamma/beta/moving stats stay fp32 — the FP32_OPS policy
    promotes their inputs at dispatch when AMP is active, and XLA fuses the
    converts).
    """
    from ..gluon import nn as _nn
    norm_types = (_nn.BatchNorm, _nn.LayerNorm, _nn.GroupNorm,
                  _nn.InstanceNorm)

    def _walk(b):
        yield b
        for c in b._children.values():
            yield from _walk(c)

    block.cast(target_dtype)
    for child in _walk(block):
        if isinstance(child, norm_types):
            child.cast("float32")
    return block
