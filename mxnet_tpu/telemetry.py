"""Runtime telemetry: instrument registry, step-phase spans, distributed
trace context, and a crash flight recorder (ISSUE 8 tentpole).

The repo could train through faults, compress its wire and compile its
whole step — but it could not *say where a step's time goes*: counters
were scattered ints on the engine, the profiler only saw eager op
dispatches, kvstore RPCs went dark past the socket, and a crashed rank
left nothing behind but an exit code.  This module is the shared
substrate the ROADMAP's serving/sharding arcs will record into
(TensorFlow treats exactly this as the precondition for production
scale — arxiv 1605.08695, PAPERS.md):

* **Instruments** — :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` in a process-wide :class:`Registry`, exposed as
  JSON (:meth:`Registry.snapshot`) and Prometheus text
  (:meth:`Registry.to_prometheus`).  Every instrument guards its state
  with its own leaf lock (never held across a call out), so the
  mxlint-concurrency pass certifies the discipline and the lock graph
  stays acyclic.  The engine's ``dispatch_count`` / ``wire_bytes`` /
  ``compiled_steps`` counters now live here; ``engine.py`` keeps them
  as aliasing properties so every existing harness still reads them.

* **Step-phase spans** — :func:`phase` wraps one phase of a training
  step (taxonomy: ``data_wait`` / ``forward`` / ``backward`` /
  ``exchange`` / ``optimizer_apply`` / ``metric_update`` /
  ``metric_drain`` / ``retrace`` / ``compiled_step`` /
  ``compiled_window``, plus the serving engine's request phases
  ``queue_wait`` / ``pad`` / ``serve_dispatch`` / ``scatter`` —
  ISSUE 9 — and the decode engine's ``prefill`` / ``decode_step`` /
  ``kv_evict`` — ISSUE 15, with per-token latency in the
  ``serve.decode.token_seconds`` histogram).  A span measures
  *dispatch* latency — it never
  syncs the device (the host-sync mxlint rule roots this file's
  helpers) — and feeds three sinks: the per-phase histogram
  (``step_phase_seconds{phase=...}``), the existing profiler
  chrome-trace (via :func:`mxnet_tpu.profiler.annotate`, so phases and
  compiled-step dispatches land in ``profiler.dumps()`` aggregates),
  and the distributed trace buffer below.

* **Distributed trace context** — :func:`rpc_span` spans carry
  (trace_id, span_id, parent_id); the kvstore client attaches the
  current context to its SEQ wire envelope and the server opens a child
  span per request, so client push/pull, server handling, retries and
  replay-cache hits become one causally linked trace.
  :func:`dump_trace` writes a per-process chrome-trace file
  (``MX_TELEMETRY_TRACE`` directory); ``tools/telemetry_dump.py``
  merges the per-worker files into a single timeline.

* **Flight recorder** — a ring of the last ``MX_TELEMETRY_RING``
  structured step records (phase durations, dispatch/wire deltas,
  retry and NaN-guard hits, throughput), appended by
  :func:`note_step` from every training lane.  :func:`dump_crash`
  writes ring + counters to ``MX_CRASH_DIR`` when the watchdog fires,
  the NaN ``raise`` policy trips, or a fit loop dies; the latest
  record rides the heartbeat file as a JSON payload
  (:func:`heartbeat_payload`) so the launch.py supervisor can print a
  live fleet status table without any wire protocol.

Timestamps are injectable-clock-aware: record ``ts`` fields read
:func:`mxnet_tpu.fault.now`, so virtual-clock chaos tests produce
coherent orderings; span *durations* are real ``perf_counter`` deltas
(a virtual clock does not advance while a real forward pass runs).
This module imports no jax — the numpy-only kvstore server process can
afford it on every request.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import fault as _fault
from . import profiler as _profiler
from .base import get_env

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "registry",
    "enabled", "tracing_enabled", "start_tracing", "stop_tracing",
    "Span", "phase", "rpc_span", "current_trace", "observe_phase",
    "FlightRecorder", "flight_recorder", "note_step",
    "heartbeat_payload", "HEARTBEAT_SCHEMA", "parse_heartbeat",
    "phase_snapshot",
    "dump_trace", "trace_events", "clear_trace", "dump_crash",
    "register_step_observer", "register_crash_section",
]


# ---------------------------------------------------------------------------
# Instruments
# ---------------------------------------------------------------------------

def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """Monotonic counter (Prometheus counter semantics, plus ``set`` so
    the engine aliases' test-reset idiom ``engine.wire_bytes = 0`` keeps
    working)."""

    kind = "counter"

    def __init__(self, name: str, doc: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.doc = doc
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def set(self, value) -> None:
        with self._lock:
            self._value = value

    def snapshot(self) -> Dict[str, Any]:
        return {"type": self.kind, "value": self.value}


class Gauge(Counter):
    """A value that can go both ways (queue depth, live sessions)."""

    kind = "gauge"

    def dec(self, n: int = 1) -> None:
        self.inc(-n)


# seconds-scale latency buckets: 100us .. 60s, roughly 2.5x apart
_DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                    2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                    30.0, 60.0)


class Histogram:
    """Fixed-bucket histogram (Prometheus histogram semantics: cumulative
    bucket counts + sum + count, plus min/max for the JSON snapshot)."""

    kind = "histogram"

    def __init__(self, name: str, doc: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.doc = doc
        self.labels = dict(labels or {})
        self.bounds = tuple(sorted(buckets or _DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            total, count = self._sum, self._count
            mn, mx = self._min, self._max
        cum: Dict[str, int] = {}
        running = 0
        for bound, c in zip(self.bounds, counts):
            running += c
            cum["%g" % bound] = running
        cum["+Inf"] = running + counts[-1]
        return {"type": self.kind, "count": count, "sum": total,
                "min": mn if count else 0.0, "max": mx if count else 0.0,
                "avg": (total / count) if count else 0.0, "buckets": cum}


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return out if not out[:1].isdigit() else "_" + out


def _escape_label_value(v) -> str:
    """Prometheus text-exposition label-value escaping (format 0.0.4):
    backslash, double-quote and newline must be escaped IN THIS ORDER
    (backslash first, or the escapes themselves get re-escaped) — a
    model name or checkpoint path containing any of them otherwise
    emits an unparseable scrape line."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = ['%s="%s"' % (_prom_name(k), _escape_label_value(v))
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{%s}" % ",".join(parts) if parts else ""


class Registry:
    """Process-wide get-or-create instrument store.

    The registry lock guards only the name→instrument dict; instrument
    state updates take the instrument's own leaf lock — no instrument
    lock is ever acquired while the registry lock is held, so the lock
    graph the mxlint-concurrency pass extracts has no telemetry cycles.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple], Any] = {}

    def _get(self, cls, name: str, doc: str,
             labels: Optional[Dict[str, str]], **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, doc=doc, labels=labels, **kwargs)
                self._instruments[key] = inst
        if not isinstance(inst, cls):
            raise ValueError(
                "telemetry instrument %r already registered as %s, not %s"
                % (name, type(inst).__name__, cls.__name__))
        return inst

    def counter(self, name: str, doc: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, doc, labels)

    def gauge(self, name: str, doc: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, doc, labels)

    def histogram(self, name: str, doc: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(Histogram, name, doc, labels, buckets=buckets)

    def instruments(self) -> List[Any]:
        with self._lock:
            return list(self._instruments.values())

    def find(self, name: str,
             labels: Optional[Dict[str, str]] = None) -> Optional[Any]:
        with self._lock:
            return self._instruments.get((name, _label_key(labels)))

    def value(self, name: str,
              labels: Optional[Dict[str, str]] = None, default=0):
        inst = self.find(name, labels)
        return inst.value if isinstance(inst, Counter) else default

    # -- exposition ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready dict keyed ``name{label=value,...}``.  Each entry
        additionally carries ``name`` and (when labeled) ``labels`` so
        consumers that merge snapshots across processes — the fleet
        collector (mxnet_tpu/fleet.py) — never have to parse the
        display key back apart."""
        out: Dict[str, Any] = {}
        for inst in self.instruments():   # copies the list; no lock held
            key = inst.name + _prom_labels(inst.labels).replace('"', "")
            entry = inst.snapshot()
            entry["name"] = inst.name
            if inst.labels:
                entry["labels"] = dict(inst.labels)
            out[key] = entry
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        by_name: Dict[str, List[Any]] = {}
        for inst in self.instruments():
            by_name.setdefault(inst.name, []).append(inst)
        lines: List[str] = []
        for name in sorted(by_name):
            insts = by_name[name]
            pname = "mx_" + _prom_name(name)
            doc = next((i.doc for i in insts if i.doc), "")
            if doc:
                # HELP text escapes backslash + newline (same format)
                doc = doc.replace("\\", "\\\\").replace("\n", "\\n")
                lines.append("# HELP %s %s" % (pname, doc))
            lines.append("# TYPE %s %s" % (pname, insts[0].kind))
            for inst in insts:
                snap = inst.snapshot()
                if snap["type"] in ("counter", "gauge"):
                    lines.append("%s%s %s" % (
                        pname, _prom_labels(inst.labels), snap["value"]))
                    continue
                for le, cum in snap["buckets"].items():
                    lines.append("%s_bucket%s %d" % (
                        pname, _prom_labels(inst.labels, 'le="%s"' % le),
                        cum))
                lines.append("%s_sum%s %g" % (
                    pname, _prom_labels(inst.labels), snap["sum"]))
                lines.append("%s_count%s %d" % (
                    pname, _prom_labels(inst.labels), snap["count"]))
        return "\n".join(lines) + "\n"


registry = Registry()


# ---------------------------------------------------------------------------
# Enablement
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """MX_TELEMETRY (default on): phase histograms + step records."""
    return bool(get_env("MX_TELEMETRY", dtype=bool))


_trace_lock = threading.Lock()
_trace_events: List[dict] = []
_trace_forced = [0]          # start_tracing() holds (tests; under _trace_lock)
_TRACE_CAP = 200_000         # drop-newest bound; a leaked trace must not OOM
_atexit_armed = [False]


def tracing_enabled() -> bool:
    """Span buffering is on: ``start_tracing()`` held, or
    ``MX_TELEMETRY_TRACE`` names a directory to flush into at exit."""
    with _trace_lock:
        if _trace_forced[0]:
            return True
    return bool(get_env("MX_TELEMETRY_TRACE", ""))


def start_tracing() -> None:
    """Force span buffering on (tests / embedders); pairs with
    :func:`stop_tracing`."""
    with _trace_lock:
        _trace_forced[0] += 1


def stop_tracing() -> None:
    with _trace_lock:
        _trace_forced[0] = max(0, _trace_forced[0] - 1)


def trace_events() -> List[dict]:
    """Snapshot of the buffered chrome-trace events."""
    with _trace_lock:
        return list(_trace_events)


def clear_trace() -> None:
    with _trace_lock:
        _trace_events.clear()


def _buffer_event(ev: dict) -> None:
    arm = False
    with _trace_lock:
        if len(_trace_events) < _TRACE_CAP:
            _trace_events.append(ev)
        if not _atexit_armed[0]:
            _atexit_armed[0] = arm = True
    if arm:
        import atexit
        atexit.register(_flush_trace_atexit)


def _flush_trace_atexit() -> None:
    try:
        if get_env("MX_TELEMETRY_TRACE", ""):
            dump_trace()
    except Exception:
        pass    # never fail interpreter shutdown over telemetry


# ---------------------------------------------------------------------------
# Spans + trace context
# ---------------------------------------------------------------------------

class _TLS(threading.local):
    def __init__(self):
        self.stack: List["Span"] = []
        self.phases: Dict[str, float] = {}


_tls = _TLS()


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace() -> Tuple[Optional[str], Optional[str]]:
    """(trace_id, span_id) of this thread's innermost open span."""
    stack = _tls.stack
    if stack:
        return stack[-1].trace_id, stack[-1].span_id
    return None, None


class Span:
    """One timed, trace-linked range.

    Context manager: entering assigns ``span_id`` and inherits (or
    creates) ``trace_id``/``parent_id`` from the thread's span stack;
    exiting buffers a chrome-trace ``X`` event (when tracing is on) and,
    while the profiler runs, a profiler span so the range lands in
    ``profiler.dumps()``.  :meth:`event` adds instant child events
    (retries, replays).  Measures dispatch latency only — it must never
    touch device buffers (hot-path lint roots this class)."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "_t0", "_wall0", "_prof_ts", "_events")

    def __init__(self, name: str, cat: str = "span",
                 trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id: Optional[str] = None
        self.parent_id = parent_id
        self._events: List[dict] = []

    def __enter__(self) -> "Span":
        cur_trace, cur_span = current_trace()
        if self.trace_id is None:
            self.trace_id = cur_trace or _new_id()
        if self.parent_id is None:
            self.parent_id = cur_span
        self.span_id = _new_id()
        _tls.stack.append(self)
        self._prof_ts = _profiler._now_us() if _profiler.RUNNING else None
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def wire_context(self) -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) to ship on an outgoing RPC envelope, or
        None before ``__enter__``/when ids were never assigned."""
        if self.span_id is None:
            return None
        return (self.trace_id, self.span_id)

    def event(self, name: str, **args) -> None:
        """Instant child event (chrome ``i`` phase) inside this span."""
        if self.span_id is None or not tracing_enabled():
            return
        self._events.append({
            "name": name, "cat": self.cat, "ph": "i", "s": "t",
            "ts": time.time() * 1e6, "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": dict(args, trace_id=self.trace_id,
                         span_id=self.span_id)})

    def _close(self, dur: float) -> None:
        if tracing_enabled():
            _buffer_event({
                "name": self.name, "cat": self.cat, "ph": "X",
                "ts": self._wall0 * 1e6, "dur": dur * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": {"trace_id": self.trace_id,
                         "span_id": self.span_id,
                         "parent_id": self.parent_id}})
            for ev in self._events:
                _buffer_event(ev)
        self._events = []
        if _profiler.RUNNING and self._prof_ts is not None:
            _profiler.record_span(self.name, self.cat, self._prof_ts,
                                  dur * 1e6)

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        stack = _tls.stack
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:            # unbalanced exit: drop through it
            stack.remove(self)
        self._close(dur)
        return False


class _PhaseSpan(Span):
    """A :class:`Span` that also accumulates into the per-phase
    histogram and this thread's current step record."""

    __slots__ = ()

    def __exit__(self, *exc) -> bool:
        dur = time.perf_counter() - self._t0
        stack = _tls.stack
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._close(dur)
        # a same-name phase still open on the stack means this was a
        # nested re-entry (Module.forward_backward wrapping a backward
        # that wraps autograd.backward): the outer span owns the
        # accounting — accumulating both would double the phase
        if enabled() and not any(isinstance(s, _PhaseSpan) and
                                 s.name == self.name for s in stack):
            pname = self.name[len("phase."):] \
                if self.name.startswith("phase.") else self.name
            _phase_hist(pname).observe(dur)
            _tls.phases[pname] = _tls.phases.get(pname, 0.0) + dur
        return False


class _NullSpan:
    """Shared no-op when every sink is off (the hot path pays three
    global reads and no allocation)."""

    __slots__ = ()
    trace_id = span_id = parent_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name, **args):
        return None

    def wire_context(self):
        return None


_NULL_SPAN = _NullSpan()

_phase_hist_lock = threading.Lock()
_phase_hists: Dict[str, Histogram] = {}


def _phase_hist(name: str) -> Histogram:
    with _phase_hist_lock:
        h = _phase_hists.get(name)
    if h is None:
        h = registry.histogram("step_phase_seconds",
                               doc="training-step phase durations "
                                   "(dispatch-time; see docs/ARCHITECTURE"
                                   ".md span taxonomy)",
                               labels={"phase": name})
        with _phase_hist_lock:
            _phase_hists[name] = h
    return h


def phase(name: str):
    """One training-step phase span (``data_wait`` / ``forward`` / ...).

    Dispatch-time semantics only: the span brackets host work and async
    XLA dispatches, never a device sync.  Returns a shared no-op when
    telemetry, tracing and the profiler are all off."""
    if not (_profiler.RUNNING or enabled() or tracing_enabled()):
        return _NULL_SPAN
    return _PhaseSpan("phase." + name, cat="phase")


def observe_phase(name: str, seconds: float) -> None:
    """Record one already-measured phase duration into the per-phase
    histogram (``step_phase_seconds{phase=name}``).

    The span form (:func:`phase`) needs the phase to be a lexical block
    on ONE thread; a duration that straddles threads — the serving
    batcher's ``queue_wait`` starts at admission on an RPC handler
    thread and ends at dequeue on the batcher thread — is measured by
    the consumer and observed here instead.  No-op when telemetry is
    off."""
    if enabled():
        _phase_hist(name).observe(float(seconds))


def rpc_span(name: str, trace_id: Optional[str] = None,
             parent_id: Optional[str] = None):
    """A wire-RPC span (kvstore client request / server handling).

    Records when tracing or the profiler is on, or when the caller
    supplies an inbound trace context (a traced client deserves a
    server-side child span even if the server's own env never enabled
    tracing — the buffered event is simply dropped at the sink)."""
    if not (tracing_enabled() or _profiler.RUNNING or trace_id):
        return _NULL_SPAN
    return Span(name, cat="rpc", trace_id=trace_id, parent_id=parent_id)


def phase_snapshot() -> Dict[str, Dict[str, float]]:
    """{phase: {count, avg_ms, total_ms, max_ms}} from the per-phase
    histograms — what bench.py embeds in its JSON report."""
    out: Dict[str, Dict[str, float]] = {}
    for inst in registry.instruments():
        if inst.name != "step_phase_seconds" or \
                not isinstance(inst, Histogram):
            continue
        snap = inst.snapshot()
        pname = inst.labels.get("phase", "?")
        if pname.startswith("phase."):
            pname = pname[len("phase."):]
        out[pname] = {
            "count": snap["count"],
            "avg_ms": round(snap["avg"] * 1e3, 4),
            "total_ms": round(snap["sum"] * 1e3, 4),
            "max_ms": round(snap["max"] * 1e3, 4),
        }
    return out


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

# counters whose per-step deltas ride every step record, and the record
# field each delta lands in
_DELTA_COUNTERS = {
    "engine.dispatch_count": "dispatches",
    "engine.wire_bytes": "wire_bytes",
    "kvstore.client_retries": "retries",
    "health.nan_events": "nan_events",
}


class FlightRecorder:
    """Ring buffer of the last N structured step records.

    Cheap by construction — one dict build + deque append per step; the
    deltas come off registry counters the hot paths were already
    bumping.  ``dump()``/:func:`dump_crash` serialize it on failure."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._capacity = capacity
        self._ring: Optional[deque] = None
        self._prev: Dict[str, int] = {}
        self._prev_t: Optional[float] = None
        self._steps = 0

    def _ensure_ring(self) -> deque:
        # lazily sized so tests can flip MX_TELEMETRY_RING before the
        # first record; resizing after that needs clear()
        if self._ring is None:
            cap = self._capacity
            if cap is None:
                try:
                    cap = int(get_env("MX_TELEMETRY_RING", 256, int) or 256)
                except (TypeError, ValueError):
                    cap = 256
            self._ring = deque(maxlen=max(1, cap))
        return self._ring

    def record(self, phases: Optional[Dict[str, float]] = None,
               steps: int = 1, epoch: Optional[int] = None,
               batch: Optional[int] = None,
               batch_size: Optional[int] = None,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Append one step record; returns it."""
        now_t = time.perf_counter()
        cur = {name: registry.value(name) for name in _DELTA_COUNTERS}
        rec: Dict[str, Any] = {
            "ts": _fault.now(),           # injectable clock: chaos tests
            "wall_time": time.time(),     # humans reading crash dumps
            "steps": int(steps),
        }
        if epoch is not None:
            rec["epoch"] = int(epoch)
        if batch is not None:
            rec["batch"] = int(batch)
        if phases:
            rec["phases"] = {k[len("phase."):] if k.startswith("phase.")
                             else k: round(v, 6) for k, v in phases.items()}
        with self._lock:
            ring = self._ensure_ring()
            prev, prev_t = self._prev, self._prev_t
            self._prev, self._prev_t = cur, now_t
            self._steps += int(steps)
            rec["step"] = self._steps
            for name, key in _DELTA_COUNTERS.items():
                rec[key] = cur[name] - prev.get(name, cur[name])
            if prev_t is not None and now_t > prev_t:
                dt = now_t - prev_t
                rec["steps_per_sec"] = round(steps / dt, 4)
                if batch_size:
                    rec["throughput"] = round(steps * batch_size / dt, 4)
            if extra:
                rec.update(extra)
            ring.append(rec)
        return rec

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring or ())

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def clear(self) -> None:
        with self._lock:
            self._ring = None
            self._prev = {}
            self._prev_t = None
            self._steps = 0


flight_recorder = FlightRecorder()

# Step observers / crash sections (ISSUE 10): jax-aware layers (the
# program census lives in mxnet_tpu/programs.py) hook in from outside so
# this module stays importable by the numpy-only kvstore server.  An
# observer returns a dict to merge into the step record (or None); a
# crash section returns a JSON-able payload keyed under its name.
_step_observers: List = []
_crash_sections: List[Tuple[str, Any]] = []


def register_step_observer(fn) -> None:
    """`fn() -> Optional[dict]` called per note_step (telemetry on);
    non-None results merge into that step's flight-recorder record."""
    _step_observers.append(fn)


def register_crash_section(name: str, fn) -> None:
    """`fn() -> payload` embedded as `name` in every crash dump."""
    _crash_sections.append((str(name), fn))


def note_step(steps: int = 1, epoch: Optional[int] = None,
              batch: Optional[int] = None,
              batch_size: Optional[int] = None,
              extra: Optional[Dict[str, Any]] = None):
    """End-of-step hook the training lanes call (Trainer.step, the fit
    loops' StepGuard, CompiledStep dispatches).  Snapshots the phase
    durations this thread accumulated since the last call and appends
    one flight-recorder record.  No-op (beyond dropping the phase
    accumulator) when telemetry is off."""
    phases = _tls.phases
    if phases:
        _tls.phases = {}
    if not enabled():
        return None
    for fn in list(_step_observers):
        try:
            obs = fn()
        except Exception:
            obs = None      # observers must never fail a training step
        if obs:
            extra = dict(extra or {})
            extra.update(obs)
    return flight_recorder.record(phases=phases, steps=steps, epoch=epoch,
                                  batch=batch, batch_size=batch_size,
                                  extra=extra)


_HEARTBEAT_FIELDS = ("step", "epoch", "batch", "steps_per_sec",
                     "throughput", "wire_bytes", "dispatches", "retries",
                     "nan_events", "phases")

# version stamp of the heartbeat JSON payload: parse_heartbeat (and
# the supervisor's import-light copy) IGNORES payloads stamped with a
# newer schema than this process understands — a mixed-version fleet's
# old reader must drop a future beat's payload rather than mis-render
# fields whose semantics changed (the head line still proves liveness)
HEARTBEAT_SCHEMA = 1


def heartbeat_payload() -> Optional[Dict[str, Any]]:
    """Compact dict of the latest step record for the heartbeat file's
    JSON line (step, throughput, last-exchange bytes, per-phase
    seconds) — what the supervisor's fleet status table renders and the
    fleet collector's degraded heartbeat-fallback scrape reads.  None
    when no step has been recorded (the heartbeat then stays the
    classic one-liner).

    ``schema`` versions the payload; ``ts`` is the record's
    injectable-clock stamp (mxnet_tpu.fault.now), which lets a
    virtual-clock supervisor compute beat ages on the SAME clock the
    beat was stamped with instead of racing wall time against st_mtime.
    """
    rec = flight_recorder.last()
    if rec is None:
        return None
    out = {k: rec[k] for k in _HEARTBEAT_FIELDS if k in rec}
    out["schema"] = HEARTBEAT_SCHEMA
    out["ts"] = rec.get("ts")
    return out


def parse_heartbeat(lines) -> Tuple[str, Dict[str, Any], int]:
    """Parse a heartbeat file's lines -> ``(head, payload, malformed)``.

    Line 1 is the classic ``<unix-time> <epoch> <batch>`` beat; line 2,
    when present, is :func:`heartbeat_payload` JSON.  A second line that
    fails to parse OR parses to a non-object (a torn write can leave
    valid-JSON garbage like a bare number) is tolerated-and-counted:
    ``payload`` comes back empty, ``malformed`` is 1, and the head line
    still proves liveness.  Consumed by the fleet collector's
    heartbeat-fallback scrape; ``tools/launch.py``'s
    ``Supervisor._read_beat`` keeps an import-light inline copy of this
    exact logic (the launcher must not import the framework on its
    happy path) — keep the two in sync."""
    head = lines[0] if lines else ""
    payload: Dict[str, Any] = {}
    malformed = 0
    if len(lines) > 1 and lines[1].strip():
        try:
            payload = json.loads(lines[1])
            if not isinstance(payload, dict):
                raise ValueError("heartbeat payload is not an object")
        except ValueError:
            payload = {}
            malformed = 1
    try:
        if payload.get("schema", HEARTBEAT_SCHEMA) > HEARTBEAT_SCHEMA:
            payload = {}    # future schema: ignore, don't mis-render
    except TypeError:
        payload = {}
        malformed = 1
    return head, payload, malformed


# ---------------------------------------------------------------------------
# Crash dumps + trace files
# ---------------------------------------------------------------------------

_dump_lock = threading.Lock()
_dump_seq = [0]


def _rank() -> str:
    return str(get_env("MX_PROCESS_ID") or
               os.environ.get("DMLC_WORKER_ID") or 0)


def dump_crash(reason: str, directory: Optional[str] = None,
               extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Write flight-recorder ring + counters snapshot to a crash-dump
    JSON under ``directory`` (default ``MX_CRASH_DIR``); returns the
    path, or None when no directory is configured.  Never raises — this
    runs on the way out of a dying process."""
    d = directory if directory is not None else \
        (get_env("MX_CRASH_DIR", "") or "")
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        with _dump_lock:
            _dump_seq[0] += 1
            seq = _dump_seq[0]
        path = os.path.join(d, "crash-rank%s-pid%d-%d.json"
                            % (_rank(), os.getpid(), seq))
        payload = {
            "reason": str(reason),
            "rank": _rank(),
            "pid": os.getpid(),
            "ts": _fault.now(),
            "wall_time": time.time(),
            "records": flight_recorder.records(),
            "counters": registry.snapshot(),
        }
        for name, fn in list(_crash_sections):
            try:
                payload[name] = fn()
            except Exception:
                payload[name] = None    # a dying process still dumps
        if extra:
            payload["extra"] = extra
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def dump_trace(path: Optional[str] = None, reset: bool = False,
               role: Optional[str] = None) -> Optional[str]:
    """Write this process's buffered spans as a chrome-trace JSON.

    Default path: ``MX_TELEMETRY_TRACE`` directory,
    ``trace-<role>-r<rank>-p<pid>.trace.json`` — what
    ``tools/telemetry_dump.py`` merges across workers/servers.
    ``role`` overrides the DMLC_ROLE-derived label (the fleet
    collector flushes its scrape spans as role ``fleet`` so they merge
    into the chrome trace as their own row)."""
    if role is None:
        role = os.environ.get("DMLC_ROLE", "worker")
    if path is None:
        d = get_env("MX_TELEMETRY_TRACE", "")
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "trace-%s-r%s-p%d.trace.json"
                            % (role, _rank(), os.getpid()))
    with _trace_lock:
        events = list(_trace_events)
        if reset:
            _trace_events.clear()
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"pid": os.getpid(), "rank": _rank(), "role": role},
    }
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path
