"""mx.rnn — the v1.x bucketed-sequence utilities.

Reference: python/mxnet/rnn/io.py (class BucketSentenceIter) — the data
side of BucketingModule: sentences are binned into fixed bucket lengths,
padded within their bucket, and each batch carries its ``bucket_key`` so the
module switches to that bucket's compiled executables.
"""
from __future__ import annotations

import bisect
import random
from typing import List, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter

from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ZoneoutCell, ResidualCell, ModifierCell)

__all__ = ["BucketSentenceIter", "RNNParams", "BaseRNNCell", "RNNCell",
           "LSTMCell", "GRUCell", "FusedRNNCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "ModifierCell"]


class BucketSentenceIter(DataIter):
    """Reference: mx.rnn.BucketSentenceIter(sentences, batch_size,
    buckets=..., invalid_label=-1, data_name='data',
    label_name='softmax_label').

    ``sentences``: list of int-id sequences.  Each is placed in the
    smallest bucket that fits (longer-than-largest are dropped with a
    warning, like the reference), padded with ``invalid_label``; labels
    are the next-token shift.  Batches are drawn bucket-by-bucket and
    carry ``bucket_key = bucket length``.
    """

    def __init__(self, sentences: Sequence[Sequence[int]], batch_size: int,
                 buckets: Optional[List[int]] = None, invalid_label: int = -1,
                 data_name: str = "data", label_name: str = "softmax_label",
                 dtype: str = "float32", layout: str = "NT", shuffle=True,
                 seed: int = 0):
        super().__init__(batch_size)
        if layout != "NT":
            raise MXNetError("BucketSentenceIter: only layout='NT' "
                             "(batch, time) is supported")
        if buckets is None:
            # reference default: one bucket per observed length with
            # enough sentences to fill a batch
            counts = {}
            for s in sentences:
                counts[len(s)] = counts.get(len(s), 0) + 1
            buckets = sorted(L for L, c in counts.items()
                             if c >= batch_size) or \
                [max(len(s) for s in sentences)]
        self.buckets = sorted(buckets)
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self._dtype = _np.dtype(dtype)
        self._shuffle = shuffle
        self._rng = random.Random(seed)

        self.data: List[List[_np.ndarray]] = [[] for _ in self.buckets]
        n_dropped = 0
        for s in sentences:
            idx = bisect.bisect_left(self.buckets, len(s))
            if idx == len(self.buckets):
                n_dropped += 1
                continue
            row = _np.full(self.buckets[idx], invalid_label, self._dtype)
            row[:len(s)] = _np.asarray(s, self._dtype)
            self.data[idx].append(row)
        if n_dropped:
            import warnings
            warnings.warn("BucketSentenceIter: dropped %d sentence(s) "
                          "longer than the largest bucket (%d)"
                          % (n_dropped, self.buckets[-1]))
        self.default_bucket_key = self.buckets[-1]
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key),
                         self._dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key),
                         self._dtype)]

    def reset(self):
        # plan (bucket_idx, start) batch slots; shuffle within buckets and
        # across the plan (the reference shuffles both)
        self._plan = []
        for i, rows in enumerate(self.data):
            if self._shuffle:
                self._rng.shuffle(rows)
            for start in range(0, len(rows) - self.batch_size + 1,
                              self.batch_size):
                self._plan.append((i, start))
        if self._shuffle:
            self._rng.shuffle(self._plan)
        self._cursor = 0

    def next(self) -> DataBatch:
        from .. import ndarray as nd
        if self._cursor >= len(self._plan):
            raise StopIteration
        bidx, start = self._plan[self._cursor]
        self._cursor += 1
        rows = self.data[bidx][start:start + self.batch_size]
        L = self.buckets[bidx]
        x = _np.stack(rows)
        # next-token labels, padded with invalid_label at the end
        y = _np.full_like(x, self.invalid_label)
        y[:, :-1] = x[:, 1:]
        return DataBatch(
            data=[nd.array(x)], label=[nd.array(y)], bucket_key=L,
            provide_data=[DataDesc(self.data_name,
                                   (self.batch_size, L), self._dtype)],
            provide_label=[DataDesc(self.label_name,
                                    (self.batch_size, L), self._dtype)])


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Reference: rnn.save_rnn_checkpoint — unpack every cell's fused
    blobs before writing the standard checkpoint pair, so the artifact
    holds per-gate matrices."""
    from ..model import save_checkpoint
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Reference: rnn.load_rnn_checkpoint — load the pair and re-pack
    per-gate matrices into each cell's fused layout."""
    from ..model import load_checkpoint
    sym, arg, aux = load_checkpoint(prefix, epoch)
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Reference: rnn.do_rnn_checkpoint — the epoch-end callback form."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback


__all__ += ["save_rnn_checkpoint", "load_rnn_checkpoint",
            "do_rnn_checkpoint"]
